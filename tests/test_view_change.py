"""View change: primary failure → complaints → new view → liveness.

Mirrors the reference's Apollo view-change suite
(tests/apollo/test_skvbc_view_change.py) at in-process scale, plus unit
tests for the ViewChangeSafetyLogic equivalent.
"""
import time

import pytest

from tpubft.apps import counter
from tpubft.consensus import messages as m
from tpubft.consensus import view_change as vc
from tpubft.testing import InProcessCluster

FAST_VC = {"view_change_timer_ms": 500}


def wait_for(pred, timeout=10.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


def test_view_change_after_primary_failure():
    with InProcessCluster(f=1, cfg_overrides=FAST_VC) as cluster:
        cluster.kill(0)                       # primary of view 0
        cl = cluster.client()
        reply = cl.send_write(counter.encode_add(5), timeout_ms=20000)
        assert counter.decode_reply(reply) == 5
        # surviving replicas all moved past view 0
        for r in (1, 2, 3):
            assert cluster.replicas[r].view >= 1
            assert cluster.replicas[r].primary != 0


def test_committed_state_survives_view_change():
    with InProcessCluster(f=1, cfg_overrides=FAST_VC) as cluster:
        cl = cluster.client()
        assert counter.decode_reply(cl.send_write(counter.encode_add(10))) == 10
        cluster.kill(0)
        reply = cl.send_write(counter.encode_add(7), timeout_ms=20000)
        assert counter.decode_reply(reply) == 17   # history preserved
        assert wait_for(lambda: all(
            cluster.handlers[r].value == 17 for r in (1, 2, 3)))


def test_progress_resumes_in_new_view():
    with InProcessCluster(f=1, cfg_overrides=FAST_VC) as cluster:
        cluster.kill(0)
        cl = cluster.client()
        total = 0
        for delta in (1, 2, 3):
            total += delta
            reply = cl.send_write(counter.encode_add(delta),
                                  timeout_ms=20000)
            assert counter.decode_reply(reply) == total


def test_cascading_view_change_two_dead_primaries():
    """f=2 (n=7): views 0 and 1 both have dead primaries; the view change
    must escalate until a live primary (replica 2) is found."""
    with InProcessCluster(f=2, cfg_overrides=FAST_VC) as cluster:
        cluster.kill(0)
        cluster.kill(1)
        cl = cluster.client()
        reply = cl.send_write(counter.encode_add(9), timeout_ms=40000)
        assert counter.decode_reply(reply) == 9
        live = [r for r in range(2, 7)]
        assert all(cluster.replicas[r].view >= 2 for r in live)


def test_view_metric_updates():
    with InProcessCluster(f=1, cfg_overrides=FAST_VC) as cluster:
        cluster.kill(0)
        cl = cluster.client()
        cl.send_write(counter.encode_add(1), timeout_ms=20000)
        assert cluster.metric(1, "gauges", "view") >= 1


def test_view_entry_fetches_missing_restricted_body():
    """ViewChangeMsgs carry batch DIGESTS only; a replica that never saw a
    restricted PrePrepare must fetch the body (ReqViewPrePrepareMsg) before
    it can enter the new view. Replica 3 is blinded to all PrePrepares in
    view 0; the commit proceeds 0+1+2 on the slow-path quorum. After the
    primary dies, the 2f+c+1 = 3 commit quorum in view 1 is exactly
    {1,2,3}, so the next write can only succeed if 3 resolved the body and
    entered the view — and executing the re-proposal gives it the value."""
    import struct

    from tpubft.consensus.messages import MsgCode

    cluster = InProcessCluster(f=1, cfg_overrides=FAST_VC)

    def blind_replica_3(sender, dest, data):
        if dest == 3 and len(data) >= 2 \
                and struct.unpack_from("<H", data)[0] == int(MsgCode.PrePrepare) \
                and not cluster.replicas[3].in_view_change \
                and cluster.replicas[3].view == 0:
            return None
        return data

    cluster.bus.add_hook(blind_replica_3)
    with cluster:
        cl = cluster.client()
        assert counter.decode_reply(
            cl.send_write(counter.encode_add(10), timeout_ms=20000)) == 10
        assert cluster.handlers[3].value == 0      # blinded: never executed
        cluster.kill(0)
        reply = cl.send_write(counter.encode_add(7), timeout_ms=30000)
        assert counter.decode_reply(reply) == 17
        assert cluster.replicas[3].view >= 1
        # the re-proposed restricted batch reached 3 via the body fetch
        assert wait_for(lambda: cluster.handlers[3].value == 17)


def test_parked_entry_unwedges_when_stability_passes_it():
    """A view entry parked on a missing restricted body must not wedge
    forever once the cluster has moved past that seqnum: when stability
    advances over the restricted seq (e.g. via state transfer), the
    unresolvable restriction is dropped and the view is entered."""
    cluster = InProcessCluster(f=1)     # not started: direct state checks
    rep = cluster.replicas[1]
    pp = m.PrePrepareMsg(
        sender_id=0, view=0, seq_num=1, first_path=2, time=0,
        requests_digest=m.PrePrepareMsg.compute_requests_digest([]),
        requests=[], signature=b"")
    r = vc.Restriction(seq_num=1, view=0, pp_digest=pp.digest(),
                       requests_digest=b"", pre_prepare=b"")
    rep.in_view_change = True
    rep.pending_view = 1
    rep._pending_entry = (1, {1: r}, {pp.digest()})
    rep._on_seq_stable(150)             # checkpoint moved past seq 1
    assert rep.view == 1
    assert not rep.in_view_change
    assert rep._pending_entry is None


# ---------------- unit: safety logic ----------------

def test_forged_certificate_rejected():
    """A certificate whose combined signature is garbage must not create a
    restriction (a byzantine replica cannot force a bogus re-proposal)."""
    pp = m.PrePrepareMsg(sender_id=0, view=0, seq_num=5, first_path=2,
                         time=0, requests_digest=b"\x00" * 32, requests=[],
                         signature=b"")
    pp.requests_digest = m.PrePrepareMsg.compute_requests_digest([])
    cert = m.PreparedCertificate(
        seq_num=5, view=0, kind=vc.CERT_PREPARE, pp_digest=pp.digest(),
        combined_sig=b"\xde\xad" * 32)

    class RejectingVerifier:
        threshold = 3

        def verify(self, digest, sig):
            return False

    from tpubft.consensus.replica import share_digest
    sd = lambda kind, view, seq, d: share_digest(kind, 0, view, seq, d)
    assert vc.validate_certificate(
        cert, sd, lambda kind: RejectingVerifier()) is None


def test_share_digest_binds_epoch():
    """The signed share digest must change with the reconfiguration era:
    a share (or combined certificate) minted in a dead epoch can never
    match the digest a current-era collector or view-change validator
    derives — the era gate no longer rests on the unauthenticated wire
    field (ADVICE r5)."""
    from tpubft.consensus.replica import share_digest
    d0 = share_digest("prepare", 0, 1, 5, b"\x07" * 32)
    d1 = share_digest("prepare", 1, 1, 5, b"\x07" * 32)
    assert d0 != d1
    # and it still separates kind / view / seq as before
    assert d0 != share_digest("commit", 0, 1, 5, b"\x07" * 32)
    assert d0 != share_digest("prepare", 0, 2, 5, b"\x07" * 32)
    assert d0 != share_digest("prepare", 0, 1, 6, b"\x07" * 32)


def test_restriction_rejects_wrong_body():
    """A fetched batch body that doesn't hash to the certified digest must
    not resolve the restriction (peers' claims are never trusted — only
    bodies matching the threshold-certified digest)."""
    pp = m.PrePrepareMsg(sender_id=0, view=0, seq_num=5, first_path=2,
                         time=0,
                         requests_digest=m.PrePrepareMsg.compute_requests_digest([]),
                         requests=[], signature=b"")
    r = vc.Restriction(seq_num=5, view=0, pp_digest=b"\x11" * 32,
                       requests_digest=b"", pre_prepare=b"")
    assert not r.resolve(pp.pack())            # digest mismatch
    assert not r.resolved
    r2 = vc.Restriction(seq_num=5, view=0, pp_digest=pp.digest(),
                        requests_digest=b"", pre_prepare=b"")
    assert not r2.resolve(b"\x00garbage")      # unparseable
    assert r2.resolve(pp.pack())               # the real body
    assert r2.resolved
    assert r2.requests_digest == pp.requests_digest
    # wrong (seq, view) with a matching digest is impossible, but the
    # structural check also guards a body for another slot
    r3 = vc.Restriction(seq_num=6, view=0, pp_digest=pp.digest(),
                        requests_digest=b"", pre_prepare=b"")
    assert not r3.resolve(pp.pack())


def test_restrictions_pick_highest_view():
    from tpubft.consensus.replica import share_digest
    sd = lambda kind, view, seq, d: share_digest(kind, 0, view, seq, d)

    class AcceptingVerifier:
        threshold = 3

        def verify(self, digest, sig):
            return True

    def make_vc(sender, view_of_cert):
        pp = m.PrePrepareMsg(
            sender_id=0, view=view_of_cert, seq_num=3, first_path=2, time=0,
            requests_digest=m.PrePrepareMsg.compute_requests_digest([]),
            requests=[], signature=b"")
        cert = m.PreparedCertificate(
            seq_num=3, view=view_of_cert, kind=vc.CERT_PREPARE,
            pp_digest=pp.digest(), combined_sig=b"sig")
        return m.ViewChangeMsg(sender_id=sender, new_view=5,
                               last_stable_seq=0, prepared=[cert],
                               signature=b"")

    restr = vc.compute_restrictions(
        [make_vc(1, 0), make_vc(2, 2), make_vc(3, 1)],
        sd, lambda kind: AcceptingVerifier(), report_quorum=2)
    assert restr[3].view == 2


def test_signed_reports_restrict_fast_path():
    """f+c+1 matching SIGNED elements (no threshold proof) must produce a
    restriction — this is the only evidence a fast-path commit leaves at
    the share signers."""
    from tpubft.consensus.replica import share_digest
    sd = lambda kind, view, seq, d: share_digest(kind, 0, view, seq, d)
    pp = m.PrePrepareMsg(
        sender_id=0, view=0, seq_num=7, first_path=0, time=0,
        requests_digest=m.PrePrepareMsg.compute_requests_digest([]),
        requests=[], signature=b"")

    def make_vc(sender):
        cert = m.PreparedCertificate(
            seq_num=7, view=0, kind=vc.CERT_SIGNED, pp_digest=pp.digest(),
            combined_sig=b"")
        return m.ViewChangeMsg(sender_id=sender, new_view=1,
                               last_stable_seq=0, prepared=[cert],
                               signature=b"")

    # below quorum: no restriction
    restr = vc.compute_restrictions([make_vc(1)], sd,
                                    lambda kind: None, report_quorum=2)
    assert 7 not in restr
    # at quorum: restricted (digest-only until the body resolves)
    restr = vc.compute_restrictions([make_vc(1), make_vc(2)], sd,
                                    lambda kind: None, report_quorum=2)
    assert restr[7].pp_digest == pp.digest()
    assert not restr[7].resolved
    assert restr[7].resolve(pp.pack())
    assert restr[7].requests_digest == pp.requests_digest


def test_state_bounded_per_sender():
    """A byzantine replica spamming complaints/VC msgs for ever-higher
    views must not grow memory: only its latest is kept."""
    st = vc.ViewChangeState(complaint_quorum=2, view_change_quorum=3)
    for view in range(1000):
        st.add_complaint(m.ReplicaAsksToLeaveViewMsg(
            sender_id=3, view=view, reason=0, signature=b""))
        st.add_view_change(m.ViewChangeMsg(
            sender_id=3, new_view=view + 1, last_stable_seq=0, prepared=[],
            signature=b""))
    assert sum(len(d) for d in st.complaints.values()) == 1
    assert sum(len(d) for d in st.vc_msgs.values()) == 1
    # stale (lower-view) messages from the same sender are ignored
    st.add_complaint(m.ReplicaAsksToLeaveViewMsg(
        sender_id=3, view=5, reason=0, signature=b""))
    assert st.complaint_count(999) == 1
    assert st.complaint_count(5) == 0


def test_restrictions_survive_crash(tmp_path):
    """Safety state persisted at view entry must reload after a crash."""
    from tpubft.consensus.persistent import FilePersistentStorage
    from tpubft.consensus.view_change import (pack_cert, pack_restriction,
                                              unpack_cert,
                                              unpack_restriction)
    pp = m.PrePrepareMsg(
        sender_id=0, view=2, seq_num=9, first_path=2, time=0,
        requests_digest=m.PrePrepareMsg.compute_requests_digest([]),
        requests=[], signature=b"")
    restriction = vc.Restriction(seq_num=9, view=2, pp_digest=pp.digest(),
                                 requests_digest=pp.requests_digest,
                                 pre_prepare=pp.pack())
    cert = m.PreparedCertificate(
        seq_num=9, view=2, kind=vc.CERT_PREPARE, pp_digest=pp.digest(),
        combined_sig=b"csig")
    path = str(tmp_path / "meta.wal")
    storage = FilePersistentStorage(path)
    st = storage.begin_write_tran()
    st.restrictions = [pack_restriction(restriction)]
    st.carried_certs = [pack_cert(cert)]
    st.carried_bodies = [pp.pack()]
    storage.end_write_tran()
    storage.close()

    reloaded = FilePersistentStorage(path).load()
    r2 = unpack_restriction(reloaded.restrictions[0])
    assert (r2.seq_num, r2.view) == (9, 2)
    assert r2.requests_digest == restriction.requests_digest
    assert r2.resolved
    c2 = unpack_cert(reloaded.carried_certs[0])
    assert (c2.seq_num, c2.kind, c2.combined_sig) == (9, vc.CERT_PREPARE,
                                                      b"csig")
    assert reloaded.carried_bodies == [pp.pack()]


def test_view_change_state_quorums():
    st = vc.ViewChangeState(complaint_quorum=2, view_change_quorum=3)
    for sender in (1, 2):
        st.add_complaint(m.ReplicaAsksToLeaveViewMsg(
            sender_id=sender, view=0, reason=0, signature=b""))
    assert st.has_complaint_quorum(0)
    assert not st.has_complaint_quorum(1)
    for sender in (0, 1, 2, 3):
        st.add_view_change(m.ViewChangeMsg(
            sender_id=sender, new_view=1, last_stable_seq=0, prepared=[],
            signature=b""))
    assert st.has_view_change_quorum(1)
    quorum = st.quorum_for_new_view(1)
    # every available msg is used (deterministic order) so no certificate
    # evidence is discarded
    assert [v.sender_id for v in quorum] == [0, 1, 2, 3]
