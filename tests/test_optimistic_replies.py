"""Optimistic reply plane (ISSUE 18 acceptance).

Covers: on/off ledger equivalence — byte-identical ledger blocks,
state digest and reply-ring pages with `optimistic_replies` on vs off
(the plane changes WHEN the client hears back, never WHICH bytes land),
including an abort-heavy schedule behind a genuinely equivocating
primary (speculative runs staged at PrePrepare acceptance abort when
the view change resolves the other fork); clients running strict
`require_signed_replies` accept the f+1 individually-signed replies;
and the durability gate — a backup's signed optimistic reply is only
sent at/after the group-commit watermark (held pipelines mean NO ack,
exactly like the certificate-gated plane of ISSUE 15)."""
import threading
import time

import pytest

from tpubft.apps import skvbc
from tpubft.consensus.persistent import FilePersistentStorage
from tpubft.kvbc import KeyValueBlockchain
from tpubft.storage.memorydb import MemoryDB
from tpubft.testing.cluster import InProcessCluster

_FAST_VC = {"view_change_timer_ms": 900}


def _wait(pred, timeout=25.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _kv_cluster(tmp_path, dbs, byzantine=None, **overrides):
    def handler_factory(r):
        db = dbs.setdefault(r, MemoryDB())
        return skvbc.SkvbcHandler(
            KeyValueBlockchain(db, use_device_hashing=False))

    def storage_factory(r):
        return FilePersistentStorage(str(tmp_path / f"r{r}.wal"))

    return InProcessCluster(f=1, handler_factory=handler_factory,
                            storage_factory=storage_factory,
                            byzantine=byzantine,
                            cfg_overrides=overrides or None)


def _run_workload(tmp_path, sub, n_writes=6, byzantine=None,
                  timeout_ms=15000, **overrides):
    """Sequential single-key writes (one block per write), deterministic
    ledger bytes; returns the observable artifacts the optimistic plane
    must NOT change."""
    dbs = {}
    subdir = tmp_path / sub
    subdir.mkdir()
    with _kv_cluster(subdir, dbs, byzantine=byzantine,
                     **overrides) as cluster:
        strict = bool(overrides.get("optimistic_replies"))
        cl = cluster.client(0, require_signed_replies=strict)
        cl._req_seq = 1_000_000     # pin reply-ring page comparability
        kv = skvbc.SkvbcClient(cl)
        for i in range(n_writes):
            assert kv.write([(b"k%d" % i, b"v%d" % i)],
                            timeout_ms=timeout_ms).success
        # compare a replica that is honest in BOTH runs (0 is the
        # byzantine primary in the abort-heavy schedule)
        ref = 1 if byzantine else 0
        assert _wait(lambda:
                     cluster.handlers[ref].blockchain.last_block_id
                     == n_writes)
        bc = cluster.handlers[ref].blockchain
        assert _wait(lambda: cluster.metric(
            ref, "counters", "dur_groups", component="durability") > 0)
        opt_fired = sum(
            cluster.metric(r, "counters", "optimistic_releases")
            for r in range(cluster.n) if r != 0 or not byzantine)
        aborts = sum(
            cluster.metric(r, "counters", "exec_spec_aborts")
            for r in range(cluster.n) if r != 0 or not byzantine)
        pages = cluster.replicas[ref].res_pages
        ring = sorted((k, v) for k, v in pages.all_pages()
                      if k[2:].startswith((b"clientreplies", b"clients")))
        return {
            "state_digest": bc.state_digest(),
            "reply_pages": ring,
            "blocks": [bc.get_raw_block(b)
                       for b in range(1, n_writes + 1)],
            "opt_fired": opt_fired,
            "spec_aborts": aborts,
        }


def test_optimistic_on_off_ledger_equivalence(tmp_path):
    """Same sequential workload with the optimistic reply plane on
    (strict signed-reply client) vs off: byte-identical ledger blocks,
    state digest, and reply-ring pages. The ON run must actually have
    exercised the plane (optimistic_releases fired)."""
    on = _run_workload(tmp_path, "on", optimistic_replies=True)
    off = _run_workload(tmp_path, "off", optimistic_replies=False)
    assert on["opt_fired"] > 0, \
        "optimistic plane never released a slot — test proved nothing"
    assert off["opt_fired"] == 0
    assert on["state_digest"] == off["state_digest"]
    assert on["reply_pages"] and on["reply_pages"] == off["reply_pages"]
    assert on["blocks"] == off["blocks"]


# ~13 s (view-change schedule): the clean on/off equivalence test above
# keeps the byte-identical pin in tier-1; the abort-heavy variant and
# the optimistic-reply-cert-blackout chaos scenario ride the slow suite
@pytest.mark.slow
def test_optimistic_equivalence_abort_heavy(tmp_path):
    """Abort-heavy schedule: an equivocating primary forks every
    PrePrepare, so backups speculate (now staged at PP ACCEPTANCE, the
    earliest point) on forks the view change then discards. Optimistic
    on vs off must still produce byte-identical ledgers and reply
    pages, and the honest replicas must have actually aborted
    speculative runs in the ON schedule."""
    on = _run_workload(tmp_path, "on", n_writes=3,
                       byzantine={0: "equivocate"}, timeout_ms=45000,
                       optimistic_replies=True, **_FAST_VC)
    off = _run_workload(tmp_path, "off", n_writes=3,
                        byzantine={0: "equivocate"}, timeout_ms=45000,
                        optimistic_replies=False, **_FAST_VC)
    assert on["spec_aborts"] > 0, \
        "equivocation schedule produced no speculative aborts"
    assert on["state_digest"] == off["state_digest"]
    assert on["reply_pages"] and on["reply_pages"] == off["reply_pages"]
    assert on["blocks"] == off["blocks"]


def test_optimistic_reply_never_precedes_group_fsync(tmp_path):
    """The optimistic plane removes the CERTIFICATE wait from the reply
    path, never the DURABILITY wait: hold every replica's io thread and
    the signed optimistic reply must not reach the client, nor
    last_executed advance past the watermark; release delivers the same
    write (PR 15 semantics, ISSUE 18 tentpole b)."""
    dbs = {}
    with _kv_cluster(tmp_path, dbs, durability_window_us=0,
                     optimistic_replies=True) as cluster:
        kv = skvbc.SkvbcClient(
            cluster.client(0, require_signed_replies=True))
        assert kv.write([(b"warm", b"w")], timeout_ms=15000).success
        assert _wait(lambda: all(
            cluster.replicas[r].last_executed >= 1
            and cluster.replicas[r].durability.idle()
            for r in range(4)))
        base = [cluster.replicas[r].last_executed for r in range(4)]
        for r in range(4):
            cluster.replicas[r].durability.hold()
        box = {}

        def bg_write():
            box["r"] = kv.write([(b"gated", b"g")], timeout_ms=30000)

        t = threading.Thread(target=bg_write, daemon=True)
        t.start()
        time.sleep(1.5)
        # optimistically released + executed (sealed) but NOT durable:
        # no signed reply, no watermark move
        assert "r" not in box, \
            "optimistic reply preceded its group's fsync"
        for r in range(4):
            rep = cluster.replicas[r]
            assert rep.last_executed == base[r], \
                "last_executed advanced past the durability watermark"
            assert rep.last_executed <= rep.durability.watermark
        for r in range(4):
            cluster.replicas[r].durability.release()
        t.join(30)
        assert box.get("r") is not None and box["r"].success
        for r in range(4):
            rep = cluster.replicas[r]
            assert _wait(lambda rep=rep:
                         rep.last_executed <= rep.durability.watermark
                         and rep.durability.idle(), 10)


def test_unsigned_reply_rejected_by_strict_client(tmp_path):
    """A strict client (`require_signed_replies`) must drop the
    unsigned replies a certificate-gated cluster sends: the write times
    out instead of being accepted on unvouched data."""
    from tpubft.bftclient.client import TimeoutError_
    dbs = {}
    with _kv_cluster(tmp_path, dbs,
                     optimistic_replies=False) as cluster:
        kv = skvbc.SkvbcClient(
            cluster.client(0, require_signed_replies=True))
        with pytest.raises(TimeoutError_):
            # a write normally acks in well under a second here — 1.2 s
            # of silence is the starvation signal, not a flaky margin
            kv.write([(b"x", b"1")], timeout_ms=1200)
        # the cluster itself executed fine — only acceptance failed
        assert _wait(lambda:
                     cluster.handlers[0].blockchain.last_block_id >= 1)
