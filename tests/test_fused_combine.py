"""Fused cross-slot combine plane + adaptive certificate scheme (ISSUE 11).

Covers: verdict equivalence between the fused `combine_batch` overrides
and the per-job reference loop (BLS and the Ed25519 multisig vector,
including bad-share identification), the CombineBatcher draining
collectors across seqnums/kinds with per-slot fault isolation, the
CertBatchVerifier stable-identity grouping, configure-time resolution of
the "adaptive" certificate scheme, and cluster-level ledger equivalence
(fused on vs off, multisig vs BLS threshold)."""
import time

import pytest

from tpubft.consensus.collectors import (CertBatchVerifier, CombineBatcher,
                                         CombineResult, ShareCollector)
from tpubft.crypto.interfaces import Cryptosystem, IThresholdVerifier
from tpubft.crypto.systems import resolve_threshold_scheme


def _jobs(cs, k, digests, bad=()):
    """Per-digest share dicts from signers 1..k; (digest_idx, signer)
    pairs in `bad` sign over a wrong digest instead."""
    signers = {i: cs.create_threshold_signer(i) for i in range(1, k + 1)}
    jobs = []
    for j, d in enumerate(digests):
        shares = {}
        for i in range(1, k + 1):
            msg = b"wrong" * 6 + b"xx" if (j, i) in bad else d
            shares[i] = signers[i].sign_share(msg)
        jobs.append((d, shares))
    return jobs


# ---------------------------------------------------------------------
# verdict equivalence: fused combine_batch vs the per-job reference loop
# ---------------------------------------------------------------------

def test_bls_fused_combine_batch_matches_loop():
    """The BLS override (segmented combine + one RLC pairing check for
    the flush + tree identification on failing jobs only) must be
    verdict- and byte-identical to the per-job loop — including the
    bad-share list and an undecodable-share job that still clears the
    threshold."""
    cs = Cryptosystem("threshold-bls", threshold=3, num_signers=4,
                      seed=b"fused-bls")
    v = cs.create_threshold_verifier()
    digests = [bytes([i]) * 32 for i in range(4)]
    jobs = _jobs(cs, 3, digests, bad={(2, 2)})
    # job 3: one undecodable share on top of a full honest quorum —
    # silently dropped, the job still combines and verifies
    jobs[3][1][4] = b"\x00" * 48
    fused = v.combine_batch(jobs)
    loop = IThresholdVerifier.combine_batch(v, jobs)
    assert fused == loop
    oks = [ok for ok, _, _ in fused]
    assert oks == [True, True, False, True]
    assert fused[2][2] == [2]          # only the guilty share identified
    # clean fast path: all jobs verify through the single RLC check
    clean = _jobs(cs, 3, digests)
    assert v.combine_batch(clean) == \
        IThresholdVerifier.combine_batch(v, clean)


def test_multisig_tpu_fused_combine_batch_matches_loop():
    """The device multisig-vector override (every job's shares in one
    batched ed25519 verify) against the loop, including the dict-order
    bad-share listing."""
    from tpubft.crypto.tpu import make_threshold_verifier
    cs = Cryptosystem("multisig-ed25519", threshold=3, num_signers=4,
                      seed=b"fused-ms")
    v = make_threshold_verifier("multisig-ed25519", 3, 4, cs.public_key,
                                cs.share_public_keys)
    digests = [bytes([i + 16]) * 32 for i in range(3)]
    jobs = _jobs(cs, 3, digests, bad={(1, 1), (1, 3)})
    fused = v.combine_batch(jobs)
    loop = IThresholdVerifier.combine_batch(v, jobs)
    assert fused == loop
    assert [ok for ok, _, _ in fused] == [True, False, True]
    assert fused[1][2] == [1, 3]
    # a good job's combined signature is the sorted (signer, sig) vector
    # and verifies as a certificate
    assert v.verify(digests[0], fused[0][1])
    # cross-cert batching: the whole flush in one call, forgery isolated
    certs = [(digests[0], fused[0][1]), (digests[2], fused[2][1]),
             (digests[1], fused[0][1])]
    assert v.verify_batch_certs(certs) == [True, True, False]
    # verdict-iterator alignment: a MULTI-bad-share cert FIRST in the
    # flush must not shift its unconsumed verdicts onto later certs
    # (short-circuiting all() left the shared iterator mid-cert)
    two_bad = bytearray(fused[0][1])
    two_bad[10] ^= 0xFF                 # corrupt share 1's sig bytes
    two_bad[80] ^= 0xFF                 # corrupt share 2's sig bytes
    first_bad = [(digests[0], bytes(two_bad)), (digests[2], fused[2][1]),
                 (digests[0], fused[0][1])]
    assert v.verify_batch_certs(first_bad) == [False, True, True]


# ---------------------------------------------------------------------
# CombineBatcher: cross-slot drain, per-slot fault isolation
# ---------------------------------------------------------------------

def test_combine_batcher_drains_across_slots_and_kinds():
    """One flush combines collectors from different seqnums AND kinds;
    a byzantine share fails only its own CombineResult — sibling slots
    in the same batch still produce certificates."""
    cs = Cryptosystem("multisig-ed25519", threshold=3, num_signers=4,
                      seed=b"batcher")
    v = cs.create_threshold_verifier()
    results = []
    flushes = []
    cb = CombineBatcher(results.append, flush_us=20000, max_batch=64,
                        on_flush=flushes.append)
    try:
        cols = []
        for seq, kind in ((1, "prepare"), (1, "commit"), (2, "prepare"),
                          (3, "prepare")):
            d = bytes([seq]) * 16 + kind.encode().ljust(16, b".")
            col = ShareCollector(0, seq, kind, d, v)
            for r in range(3):             # 0-based replica ids
                col.add_share(r, cs.create_threshold_signer(r + 1)
                              .sign_share(d))
            cols.append(col)
        # poison ONE share of seq 2's collector
        cols[2].shares[2] = b"\x11" * 64
        for col in cols:
            cb.submit(col, dict(col.shares))
        deadline = time.monotonic() + 10
        while len(results) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        cb.stop()
    assert len(results) == 4
    by_key = {(r.seq_num, r.kind): r for r in results}
    assert by_key[(1, "prepare")].ok and by_key[(1, "commit")].ok \
        and by_key[(3, "prepare")].ok
    guilty = by_key[(2, "prepare")]
    assert not guilty.ok and guilty.bad_shares == [2]
    for r in results:
        assert r.collector is not None
        if r.ok:
            assert v.verify(r.collector.digest, r.combined_sig)
    # the whole submission drained as one flush (metrics sensor)
    assert flushes and flushes[0] == 4


def test_combine_batcher_stop_resolves_pending():
    """A stopped batcher must resolve queued jobs as combine failures
    (carrying the collector) so the dispatcher-side state flip can
    still clear job_launched."""
    cs = Cryptosystem("multisig-ed25519", threshold=2, num_signers=3,
                      seed=b"drop")
    v = cs.create_threshold_verifier()
    results = []
    cb = CombineBatcher(results.append, flush_us=10_000_000,
                        max_batch=1024)
    col = ShareCollector(0, 9, "commit", b"d" * 32, v)
    cb.stop()
    cb.submit(col, {})
    assert len(results) == 1
    res = results[0]
    assert not res.ok and res.collector is col
    col.job_launched = True
    col.on_result(res)
    assert not col.job_launched and col.combined is None


def test_cert_batcher_never_comingles_verifiers():
    """Two verifier objects in one flush: each cert verifies against
    its own verifier (the stable object key), so cluster A's cert must
    fail under cluster B even when batched together."""
    a = Cryptosystem("multisig-ed25519", 2, 3, seed=b"A")
    b = Cryptosystem("multisig-ed25519", 2, 3, seed=b"B")
    va, vb = a.create_threshold_verifier(), b.create_threshold_verifier()
    d = b"c" * 32

    def cert(cs, v):
        acc = v.new_accumulator(False)
        acc.set_expected_digest(d)
        for i in (1, 2):
            acc.add(i, cs.create_threshold_signer(i).sign_share(d))
        return acc.get_full_signed_data()

    ca, cb_ = cert(a, va), cert(b, vb)
    verdicts = {}
    bv = CertBatchVerifier(lambda cookie, ok: verdicts.update({cookie: ok}),
                           flush_us=1)
    try:
        bv._drain([(va, d, ca, "a-own"), (vb, d, cb_, "b-own"),
                   (vb, d, ca, "a-under-b")])
    finally:
        bv.stop()
    assert verdicts == {"a-own": True, "b-own": True, "a-under-b": False}


# ---------------------------------------------------------------------
# adaptive certificate scheme (configure-time resolution)
# ---------------------------------------------------------------------

def test_adaptive_scheme_resolves_by_cluster_size():
    assert resolve_threshold_scheme("adaptive", 4) == "multisig-ed25519"
    assert resolve_threshold_scheme("adaptive", 7) == "multisig-ed25519"
    assert resolve_threshold_scheme("adaptive", 16) == "threshold-bls"
    assert resolve_threshold_scheme("adaptive", 31) == "threshold-bls"
    # explicit crossover knob wins over the measured default
    assert resolve_threshold_scheme("adaptive", 4, crossover_n=2) \
        == "threshold-bls"
    # concrete schemes pass through untouched
    assert resolve_threshold_scheme("threshold-bls", 4) == "threshold-bls"
    assert resolve_threshold_scheme("multisig-ed25519", 100) \
        == "multisig-ed25519"
    # "adaptive" must never reach the cryptosystem registry unresolved
    with pytest.raises(ValueError):
        Cryptosystem("adaptive", 3, 4, seed=b"x")


def test_cluster_keys_resolve_adaptive_at_keygen():
    from tpubft.consensus.keys import ClusterKeys
    from tpubft.utils.config import ReplicaConfig
    cfg = ReplicaConfig(f_val=1, threshold_scheme="adaptive")
    ck = ClusterKeys.generate(cfg, num_clients=1, seed=b"adapt")
    assert ck.threshold_scheme == "multisig-ed25519"        # n=4
    assert ck.slow_path_system.type_name == "multisig-ed25519"
    cfg2 = ReplicaConfig(f_val=1, threshold_scheme="adaptive",
                         threshold_scheme_crossover_n=4)
    ck2 = ClusterKeys.generate(cfg2, num_clients=1, seed=b"adapt")
    assert ck2.threshold_scheme == "threshold-bls"
    assert ck2.optimistic_system.type_name == "threshold-bls"


# ---------------------------------------------------------------------
# cluster-level equivalence (the ISSUE 11 acceptance bars)
# ---------------------------------------------------------------------

def _wait(pred, timeout=25.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _run_workload(scheme: str, fused: bool, n_writes: int = 5):
    from tpubft.apps import skvbc
    from tpubft.kvbc import KeyValueBlockchain
    from tpubft.storage.memorydb import MemoryDB
    from tpubft.testing.cluster import InProcessCluster

    def handler_factory(_r):
        return skvbc.SkvbcHandler(
            KeyValueBlockchain(MemoryDB(), use_device_hashing=False))

    overrides = dict(threshold_scheme=scheme, fused_combine=fused)
    with InProcessCluster(f=1, handler_factory=handler_factory,
                          cfg_overrides=overrides) as cluster:
        cl = cluster.client(0)
        cl._req_seq = 1_000_000        # comparable reply-ring pages
        kv = skvbc.SkvbcClient(cl)
        for i in range(n_writes):
            assert kv.write([(b"k%d" % i, b"v%d" % i)],
                            timeout_ms=30000).success
        assert _wait(lambda: all(
            cluster.handlers[r].blockchain.last_block_id == n_writes
            for r in range(4)))
        bc = cluster.handlers[0].blockchain
        return {
            "state_digest": bc.state_digest(),
            "blocks": [bc.get_raw_block(i)
                       for i in range(1, n_writes + 1)],
            "combine_batches":
                cluster.metric(0, "counters", "combine_batches"),
        }


def test_fused_on_off_ledger_equivalence():
    """The same workload with the fused combine plane on vs off ends in
    byte-identical ledgers, and the fused run actually used the
    batcher."""
    on = _run_workload("multisig-ed25519", fused=True)
    off = _run_workload("multisig-ed25519", fused=False)
    assert on["state_digest"] == off["state_digest"]
    assert on["blocks"] == off["blocks"]
    assert on["combine_batches"] > 0
    assert off["combine_batches"] == 0


@pytest.mark.slow
def test_scheme_equivalence_byte_identical_ledgers():
    """A cluster certifying with the Ed25519 multisig vector and one
    with BLS threshold order the same workload into byte-identical
    ledgers — certificates are consensus metadata, never ledger state,
    so the adaptive scheme can flip per deployment without a state
    migration."""
    ms = _run_workload("multisig-ed25519", fused=True)
    bls = _run_workload("threshold-bls", fused=True)
    assert ms["state_digest"] == bls["state_digest"]
    assert ms["blocks"] == bls["blocks"]
