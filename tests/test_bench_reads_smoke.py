"""Tier-1 wiring for benchmarks/bench_reads.py (--smoke shape),
mirroring test_bench_e2e_smoke: the read-scaling serving plane — the
thin-replica tier fed from the coalesced commit stream, checkpoint-
anchored verified reads, and the pre-execution write path — gets a
collection-time guard (the bench module must import) and a runtime
guard (both read modes must serve real traffic while writes order).

TPUBFT_THREADCHECK=1 arms utils/racecheck across the run: the
commit-stream hop (exec lane → trs.subs lock), the anchor snapshot
(dispatcher → trs.anchor lock), and the preexec pool handoff all
become CheckedLock edges in the global lock-order graph, so an
inversion raises here instead of deadlocking a serving tier."""
import pytest


@pytest.fixture
def threadcheck(monkeypatch):
    monkeypatch.setenv("TPUBFT_THREADCHECK", "1")
    from tpubft.utils import racecheck
    assert racecheck.enabled()
    yield


# ~28 s; the thin-replica tests keep the verified-read plane pinned
# in tier-1, the full bench smoke rides the slow suite
@pytest.mark.slow
def test_bench_reads_smoke(threadcheck):
    from benchmarks.bench_reads import smoke
    out = smoke(secs=2.0)
    # both rows served real traffic (degraded rows carry probe_error —
    # the PR 4 artifact convention — and fail this gate loudly)
    assert out["thin"]["ok"], out
    assert out["consensus"]["ok"], out
    # EVERY thin read verified its inclusion proof against the
    # f+1-signed checkpoint anchor
    assert out["thin"]["all_verified"], out
    # a corrupting server is DETECTED, never served as data
    assert out["corrupt_server_detected"], out
    assert out["honest_read_ok"], out
    # no dispatcher/executor/serving-tier stall during the run
    assert out["stall_reports"] == 0, out
