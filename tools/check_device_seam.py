"""Device-seam lint: every kernel call site goes through the breaker.

CLI/back-compat shim — the implementation now lives in the unified
analyzer framework (tools/tpulint/passes/device_seam.py; run everything
with `python -m tools.tpulint`). Any reference to the raw
`device_dispatch` gate — import, call, or attribute — outside
tpubft/ops/dispatch.py bypasses failure classification, the OPEN
fast-fail, and half-open probe accounting, so it is rejected by
construction; a zero-module scan fails loudly.

Usage:
  python tools/check_device_seam.py [root]    # default: the repo root
Exit 1 with one line per violation. Wired into tier-1 by
tests/test_check_device_seam.py.
"""
from __future__ import annotations

import os
import sys
from typing import List

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.tpulint.passes import device_seam as _impl  # noqa: E402

FORBIDDEN = _impl.FORBIDDEN
ALLOWED = set(_impl.ALLOWED)


def find_violations(root: str):
    return _impl.find_violations(root, forbidden=FORBIDDEN,
                                 allowed=ALLOWED)


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else _ROOT
    violations = find_violations(root)
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        return 1
    print("OK: no naked device_dispatch call sites outside the breaker "
          "seam")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
