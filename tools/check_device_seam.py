"""Device-seam lint: every kernel call site goes through the breaker.

The degradation plane (tpubft/utils/breaker.py + ops/dispatch.py) only
works if NOTHING dispatches to the accelerator outside the
breaker-guarded `device_section(kind)` seam: a naked
`device_dispatch()` call site would bypass failure classification, the
OPEN fast-fail, and the half-open probe accounting — a device loss
would wedge or crash that caller instead of degrading it to its scalar
fallback. Like tools/check_hotpath.py, the property is enforced by
construction: this lint (wired into tier-1 by
tests/test_check_device_seam.py) parses every module under tpubft/ and
rejects any reference to `device_dispatch` — import, call, or
attribute — outside `tpubft/ops/dispatch.py` itself, where the raw
gate lives.

Usage:
  python tools/check_device_seam.py [root]    # default: the repo root
Exit 1 with one line per violation.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

FORBIDDEN = "device_dispatch"
# the one module allowed to touch the raw gate (it defines it and wraps
# it in the breaker-guarded device_section)
ALLOWED = {os.path.join("tpubft", "ops", "dispatch.py")}


def _scan_module(path: str, rel: str) -> List[Tuple[str, int, str]]:
    with open(path, "rb") as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            return [(rel, e.lineno or 0, f"syntax error: {e.msg}")]
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(tree):
        hit = None
        if isinstance(node, ast.Name) and node.id == FORBIDDEN:
            hit = f"references {FORBIDDEN}"
        elif isinstance(node, ast.Attribute) and node.attr == FORBIDDEN:
            hit = f"references .{FORBIDDEN}"
        elif isinstance(node, ast.ImportFrom) \
                and any(a.name == FORBIDDEN for a in node.names):
            hit = f"imports {FORBIDDEN}"
        if hit:
            out.append((rel, node.lineno,
                        f"{hit} — kernel call sites must use the "
                        f"breaker-guarded device_section(kind) seam "
                        f"(tpubft/ops/dispatch.py)"))
    return out


def find_violations(root: str) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    pkg = os.path.join(root, "tpubft")
    scanned = 0
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            scanned += 1
            if rel in ALLOWED:
                continue
            out.extend(_scan_module(path, rel))
    if not scanned:
        # a wrong root (or a package rename) must FAIL, not report a
        # vacuous OK — the enforced-by-construction property would
        # silently stop being enforced
        out.append((pkg, 0, "no Python modules found to scan — wrong "
                            "root? (expected <root>/tpubft/**/*.py)"))
    return sorted(out)


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = find_violations(root)
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        return 1
    print("OK: no naked device_dispatch call sites outside the breaker "
          "seam")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
