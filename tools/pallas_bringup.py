"""Mosaic bring-up ladder for the fused Ed25519 Pallas kernel.

The full kernel (`tpubft/ops/ed25519_pallas.py`) has only ever run under
the Pallas interpreter; this script compiles a ladder of sub-kernels of
increasing complexity ON THE REAL DEVICE so that, if the full kernel
fails Mosaic compilation, the failing construct is isolated in minutes
instead of being a single opaque error at the end of an hours-long
tunnel window. Run during a device window:

    python -m tools.pallas_bringup            # whole ladder
    python -m tools.pallas_bringup --rung 3   # one rung

Rungs (each builds on the constructs of the previous):
  0  vmem-roundtrip  3D (NL, 8, T8) block copy in/out
  1  carry           vector shift-by-vector + concat row shift (_carry24)
  2  mul             full field multiply (broadcast-MACs + _reduce48)
  3  inv             the 254-sqr/mul inversion chain under fori_loop
  4  table           scratch-ref table build + masked gather (the
                     [h](-A) table pattern, incl. btab lane-slice reads)
  5  full            the production verify_kernel on one tile, checked
                     bit-exact against the XLA kernel's verdicts

Every rung checks numerics against the pure-XLA formulation, so a rung
that compiles but miscompiles (wrong layout, bad shift lowering) is also
caught here, not in consensus.
"""
from __future__ import annotations

import argparse
import functools
import sys
import time
import traceback

import os

import jax

# on this host the tunneled-TPU plugin makes device init hang under the
# JAX_PLATFORMS=cpu env var alone; the config update is the reliable path
# (same quirk handling as tests/conftest.py and benchmarks/common.py)
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubft.ops import f25519 as F
from tpubft.ops import ed25519 as ops
from tpubft.ops import ed25519_pallas as kp

NL = F.NL
SUB = kp.SUB
TILE = kp.TILE
T8 = TILE // SUB


def _rand_elems(rng: np.random.Generator, n: int) -> np.ndarray:
    """(n,) random field elements as (NL, n) limb arrays."""
    vals = [int.from_bytes(rng.bytes(32), "little") % F.P for _ in range(n)]
    return np.stack([F.int_to_limbs(v) for v in vals], axis=1).astype(np.int32)


def _shaped(x: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(x.reshape(x.shape[0], SUB, T8))


def _consts() -> jnp.ndarray:
    return jnp.asarray(kp._consts_table())


_CONST_SPEC = pl.BlockSpec((2 * NL, 128), lambda: (0, 0),
                           memory_space=pltpu.VMEM)
_ELEM_SPEC = pl.BlockSpec((NL, SUB, T8), lambda: (0, 0, 0),
                          memory_space=pltpu.VMEM)


def _run_elemwise(kernel_body, n_elem_inputs: int, *arrays):
    """pallas_call with n (NL,8,T8) element inputs + the consts table."""
    out = pl.pallas_call(
        kernel_body,
        in_specs=[_ELEM_SPEC] * n_elem_inputs + [_CONST_SPEC],
        out_specs=_ELEM_SPEC,
        out_shape=jax.ShapeDtypeStruct((NL, SUB, T8), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=32 * 1024 * 1024),
    )(*[_shaped(a) for a in arrays], _consts())
    return np.asarray(out).reshape(NL, TILE)


# ---- rung bodies ----

def _body_copy(a_ref, consts_ref, out_ref):
    out_ref[:] = a_ref[:] + consts_ref[0, 0]   # touch both inputs


def _body_carry(a_ref, consts_ref, out_ref):
    e = kp._Engine(consts_ref)
    out_ref[:] = e.normalize(a_ref[:])


def _body_mul(a_ref, b_ref, consts_ref, out_ref):
    e = kp._Engine(consts_ref)
    out_ref[:] = e.mul(a_ref[:], b_ref[:])


def _body_inv(a_ref, consts_ref, out_ref):
    e = kp._Engine(consts_ref)
    out_ref[:] = e.inv(a_ref[:])


def _body_table(a_ref, btab_ref, consts_ref, out_ref, atab_ref):
    """The table-build + masked-gather pattern from the production step
    function: scratch writes at static indices, btab lane-slice reads,
    mask-accumulate selects."""
    e = kp._Engine(consts_ref)
    a = a_ref[:]
    atab_ref[0] = a
    cur = a
    for j in range(1, 4):
        cur = e.mul(cur, a)
        atab_ref[j] = cur
    idx = (a[0] & 3)                      # (8, T8) pseudo-window digits
    sel = None
    for j in range(4):
        term = jnp.where((idx == j)[None], atab_ref[j], 0)
        sel = term if sel is None else sel + term
    col = btab_ref[:, 0:1][:, :, None]    # lane-slice read, (NL, 1, 1)
    out_ref[:] = e.mul(sel, jnp.broadcast_to(col, sel.shape))


# ---- rungs ----

def rung0(rng):
    a = _rand_elems(rng, TILE)
    got = _run_elemwise(_body_copy, 1, a)
    want = a + int(kp._consts_table()[0, 0])
    assert np.array_equal(got, want), "vmem roundtrip mismatch"


def rung1(rng):
    a = _rand_elems(rng, TILE) * 7        # force carries
    got = _run_elemwise(_body_carry, 1, a)
    # check against limb semantics directly: same value mod p
    for i in range(0, TILE, 257):
        g = F.limbs_to_int(got[:, i]) % F.P
        w = F.limbs_to_int(a[:, i]) % F.P
        assert g == w, f"carry changed value at lane {i}"


def rung2(rng):
    a = _rand_elems(rng, TILE)
    b = _rand_elems(rng, TILE)
    got = _run_elemwise(_body_mul, 2, a, b)
    for i in range(0, TILE, 257):
        g = F.limbs_to_int(got[:, i]) % F.P
        w = (F.limbs_to_int(a[:, i]) * F.limbs_to_int(b[:, i])) % F.P
        assert g == w, f"mul mismatch at lane {i}"


def rung3(rng):
    a = _rand_elems(rng, TILE)
    got = _run_elemwise(_body_inv, 1, a)
    for i in range(0, TILE, 509):
        g = F.limbs_to_int(got[:, i]) % F.P
        w = pow(F.limbs_to_int(a[:, i]), F.P - 2, F.P)
        assert g == w, f"inv mismatch at lane {i}"


def rung4(rng):
    a = _rand_elems(rng, TILE)
    btab = jnp.asarray(kp._btab_transposed())
    out = pl.pallas_call(
        _body_table,
        in_specs=[_ELEM_SPEC,
                  pl.BlockSpec(btab.shape, lambda: (0, 0),
                               memory_space=pltpu.VMEM),
                  _CONST_SPEC],
        out_specs=_ELEM_SPEC,
        out_shape=jax.ShapeDtypeStruct((NL, SUB, T8), jnp.int32),
        scratch_shapes=[pltpu.VMEM((4, NL, SUB, T8), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=32 * 1024 * 1024),
    )(_shaped(a), btab, _consts())
    got = np.asarray(out).reshape(NL, TILE)
    col0 = F.limbs_to_int(np.asarray(kp._btab_transposed())[:, 0])
    for i in range(0, TILE, 257):
        av = F.limbs_to_int(a[:, i])
        k = int(a[0, i]) & 3
        want = (pow(av, k + 1, F.P) * col0) % F.P
        assert F.limbs_to_int(got[:, i]) % F.P == want, \
            f"table gather mismatch at lane {i}"


_INTERPRET = False


def rung5(rng):
    from tpubft.crypto import cpu as ccpu
    msgs = [rng.bytes(32) for _ in range(TILE)]
    signer = ccpu.Ed25519Signer.generate(seed=b"bringup")
    pk = signer.public_bytes()
    items = [(m, signer.sign(m), pk) for m in msgs]
    bad = rng.integers(0, TILE, size=7)
    for i in bad:
        m, s, p = items[i]
        items[i] = (m, s[:10] + bytes([s[10] ^ 1]) + s[11:], p)
    prep = ops.prepare_batch(items)
    args = (prep.s_win, prep.h_win, prep.a_y, prep.a_sign,
            prep.r_y, prep.r_sign)
    kernel = kp.verify_kernel.__wrapped__ if _INTERPRET else kp.verify_kernel
    got = np.asarray(kernel(*args))
    want = np.asarray(ops.verify_kernel(*args))
    assert np.array_equal(got, want), "full kernel disagrees with XLA"
    assert not got[list(bad)].any(), "corrupted sigs accepted"


RUNGS = [("vmem-roundtrip", rung0), ("carry", rung1), ("mul", rung2),
         ("inv", rung3), ("table+scratch", rung4), ("full-verify", rung5)]


def main() -> int:
    global _INTERPRET
    ap = argparse.ArgumentParser()
    ap.add_argument("--rung", type=int, default=None,
                    choices=range(len(RUNGS)))
    ap.add_argument("--interpret", action="store_true",
                    help="run under the Pallas interpreter (CPU self-test "
                         "of the ladder itself; no Mosaic)")
    args = ap.parse_args()
    if args.interpret:
        # interpret mode must never touch the tunneled device — force the
        # CPU platform BEFORE the first backend init below (env var alone
        # is unreliable on this box; see module header)
        jax.config.update("jax_platforms", "cpu")
        _INTERPRET = True
    print(f"platform={jax.devices()[0].platform} tile={TILE}")
    if args.interpret:
        real_call = pl.pallas_call

        def interp_call(*a, **kw):
            kw.pop("compiler_params", None)
            kw["interpret"] = True
            return real_call(*a, **kw)

        pl.pallas_call = interp_call
    todo = ([RUNGS[args.rung]] if args.rung is not None else RUNGS)
    ok = True
    for name, fn in todo:
        rng = np.random.default_rng(7)
        t0 = time.perf_counter()
        try:
            fn(rng)
            print(f"rung {name}: OK ({time.perf_counter()-t0:.1f}s)")
        except Exception:
            ok = False
            print(f"rung {name}: FAIL ({time.perf_counter()-t0:.1f}s)")
            traceback.print_exc()
            break   # later rungs share the failing construct
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
