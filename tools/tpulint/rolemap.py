"""Thread-role seed table — the analyzer's ground truth about which
code runs where.

Every thread the process creates must have its entry point listed in
THREAD_ROLES; the thread-role pass fails on a `threading.Thread(target=
<repo function>)` whose target is missing here (an unseeded thread is
unanalyzed code — the same loud-failure convention as a renamed
check_hotpath handler). Roles then propagate through the call graph,
plus two callback rules:

  * functions registered on the consensus dispatcher (`add_timer`,
    `register_internal`, `set_external_handler`, `set_admitted_handler`,
    `set_post_hook`) run with the `dispatcher` role;
  * health-probe callbacks (`register_probe` / `register_degraded_flag`)
    run with the `health` role.

API_SEEDS names cross-thread *surfaces* the syntactic call graph cannot
see through (callables stored into attributes at wiring time): the
dispatcher's incoming queue is fed by transports, admission workers and
the execution lane; the admission ingest is fed by transports; the
client library is driven by arbitrary application threads. Adding a new
thread entry point = one line here (plus a justification in the commit);
see docs/OPERATIONS.md "Static analysis & concurrency lint".
"""
from __future__ import annotations

from typing import Dict, FrozenSet, Tuple

FuncId = Tuple[str, str, str]   # (module rel, class name or None, func)

# -- thread entry points (threading.Thread targets) --------------------
THREAD_ROLES: Dict[FuncId, FrozenSet[str]] = {
    # consensus planes
    ("tpubft/consensus/incoming.py", "Dispatcher", "_loop"):
        frozenset({"dispatcher"}),
    ("tpubft/consensus/execution.py", "ExecutionLane", "_loop"):
        frozenset({"exec_lane"}),
    # group-commit durability io thread (tpubft/durability/): drains
    # the lane's sealed runs, applies + fsyncs per group, then crosses
    # into the lane's completed queue (lane condition), the
    # ClientsManager reply cache (its own lock) and the dispatcher
    # wakeup queue — all lock-guarded surfaces
    ("tpubft/durability/pipeline.py", "DurabilityPipeline", "_loop"):
        frozenset({"durability"}),
    ("tpubft/consensus/admission.py", "AdmissionPipeline", "_run"):
        frozenset({"admission"}),
    ("tpubft/consensus/health.py", "HealthMonitor", "_run"):
        frozenset({"health"}),
    # autotuner control loop (tpubft/tuning/): the ONLY role that may
    # store knob values post-wiring — every store goes through
    # KnobRegistry.set under the registry lock, and the static-race
    # pass catches a knob store from any other role (see the knob-store
    # fixture in tests/test_tpulint.py)
    ("tpubft/tuning/controller.py", "TuningController", "_run"):
        frozenset({"tuner"}),
    # infrastructure
    ("tpubft/utils/racecheck.py", "StallWatchdog", "_run"):
        frozenset({"watchdog"}),
    ("tpubft/utils/batcher.py", "FlushBatcher", "_run"):
        frozenset({"batcher"}),
    # sig-combine worker pool (ThreadPoolExecutor — invisible to the
    # threading.Thread audit, seeded directly) and the FlushBatcher
    # drain callbacks it hands off to (callable-attribute seam like
    # API_SEEDS): the combine plane's cross-thread surface against the
    # dispatcher-owned ShareCollector state
    ("tpubft/consensus/collectors.py", "CollectorPool", "_run"):
        frozenset({"sig_combine"}),
    ("tpubft/consensus/collectors.py", "CombineBatcher", "_drain"):
        frozenset({"batcher"}),
    ("tpubft/consensus/collectors.py", "CertBatchVerifier", "_drain"):
        frozenset({"batcher"}),
    ("tpubft/utils/metrics.py", "UdpMetricsServer", "_run"):
        frozenset({"metrics"}),
    # transports
    ("tpubft/comm/udp.py", "PlainUdpCommunication", "_recv_loop"):
        frozenset({"transport"}),
    ("tpubft/comm/loopback.py", "LoopbackBus", "_pump"):
        frozenset({"transport"}),
    ("tpubft/comm/tcp.py", "PlainTcpCommunication", "_accept_loop"):
        frozenset({"transport"}),
    ("tpubft/comm/tcp.py", "PlainTcpCommunication", "_connect_loop"):
        frozenset({"transport"}),
    ("tpubft/comm/tcp.py", "PlainTcpCommunication",
     "_connect_loop.dial_one"): frozenset({"transport"}),
    ("tpubft/comm/tcp.py", "PlainTcpCommunication", "_inbound_handshake"):
        frozenset({"transport"}),
    ("tpubft/comm/tcp.py", "_Peer", "_write_loop"):
        frozenset({"transport"}),
    ("tpubft/comm/tcp.py", "_Peer", "_read_loop"):
        frozenset({"transport"}),
    # serving tiers
    ("tpubft/diagnostics/server.py", "DiagnosticsServer", "_accept_loop"):
        frozenset({"diagnostics"}),
    ("tpubft/diagnostics/server.py", "DiagnosticsServer", "_serve"):
        frozenset({"diagnostics"}),
    ("tpubft/offload/helper.py", "HelperDaemon", "_accept_loop"):
        frozenset({"offload_helper"}),
    ("tpubft/offload/helper.py", "HelperDaemon", "_serve"):
        frozenset({"offload_helper"}),
    ("tpubft/thinreplica/server.py", "ThinReplicaServer", "_accept_loop"):
        frozenset({"thinreplica_srv"}),
    ("tpubft/thinreplica/server.py", "ThinReplicaServer", "_serve"):
        frozenset({"thinreplica_srv"}),
    ("tpubft/thinreplica/client.py", "ThinReplicaClient", "_supervise"):
        frozenset({"thinreplica_cli"}),
    ("tpubft/thinreplica/client.py", "ThinReplicaClient", "_data_loop"):
        frozenset({"thinreplica_cli"}),
    ("tpubft/thinreplica/client.py", "ThinReplicaClient", "_hash_loop"):
        frozenset({"thinreplica_cli"}),
    ("tpubft/client/clientservice.py", "ClientService", "_accept_loop"):
        frozenset({"client_api"}),
    ("tpubft/client/clientservice.py", "ClientService", "_serve"):
        frozenset({"client_api"}),
    # background snapshot writer (reconfiguration DbCheckpoint)
    ("tpubft/reconfiguration/dispatcher.py", "DbCheckpointHandler",
     "_try_checkpoint"):
        frozenset({"db_checkpoint"}),
    # client-side poll loop (client reconfiguration engine)
    ("tpubft/client/cre.py", "ClientReconfigurationEngine", "_loop"):
        frozenset({"cre"}),
    # load-generator worker threads (apps/tester_client CLI)
    ("tpubft/apps/tester_client.py", None, "run_workload.worker"):
        frozenset({"load_gen"}),
    # pre-execution worker pool (ThreadPoolExecutor — invisible to the
    # threading.Thread audit, seeded directly like CollectorPool): runs
    # handler.pre_execute off the dispatcher and re-enters through the
    # internal queue
    ("tpubft/preprocessor/preprocessor.py", "PreProcessor",
     "_launch.job"): frozenset({"preexec"}),
}

# -- cross-thread API surfaces (callable-attribute seams) --------------
API_SEEDS: Dict[FuncId, FrozenSet[str]] = {
    # the dispatcher's incoming queue: transports push raw datagrams,
    # admission workers push AdmittedMsgs (the pipeline `sink`), the
    # execution lane and collector completions push internal wakeups
    ("tpubft/consensus/incoming.py", "IncomingMsgsStorage",
     "push_external"): frozenset({"transport"}),
    ("tpubft/consensus/incoming.py", "IncomingMsgsStorage",
     "push_external_obj"): frozenset({"transport", "admission"}),
    ("tpubft/consensus/incoming.py", "IncomingMsgsStorage",
     "push_internal"): frozenset({"transport", "exec_lane",
                                  "dispatcher", "preexec",
                                  "sig_combine"}),
    ("tpubft/consensus/incoming.py", "IncomingMsgsStorage",
     "push_internal_once"): frozenset({"exec_lane", "durability"}),
    # the pipeline's post-fsync completion hop into the lane's
    # completed queue (callable reached through the replica attribute,
    # which the syntactic call graph cannot type)
    ("tpubft/consensus/execution.py", "ExecutionLane",
     "complete_durable"): frozenset({"durability"}),
    # admission ingest: called from transport receive threads
    ("tpubft/consensus/admission.py", "AdmissionPipeline", "submit"):
        frozenset({"transport"}),
    ("tpubft/consensus/admission.py", "AdmissionPipeline",
     "submit_burst"): frozenset({"transport"}),
    # client library: driven by arbitrary application threads AND fed
    # replies by its transport receive thread
    ("tpubft/bftclient/client.py", "BftClient", "send_write"):
        frozenset({"client_api"}),
    ("tpubft/bftclient/client.py", "BftClient", "send_read"):
        frozenset({"client_api"}),
    ("tpubft/bftclient/client.py", "BftClient", "send_write_batch"):
        frozenset({"client_api"}),
    ("tpubft/bftclient/client.py", "BftClient", "on_new_message"):
        frozenset({"transport"}),
    # session multiplexer (ISSUE 19): like the raw client sends, mux
    # sessions are driven by arbitrary application threads — the
    # per-session lane lock and per-principal semaphore are the
    # cross-thread surface in front of the shared BftClient
    ("tpubft/bftclient/pool.py", "MuxSession", "write"):
        frozenset({"client_api"}),
    ("tpubft/bftclient/pool.py", "MuxSession", "read"):
        frozenset({"client_api"}),
    ("tpubft/bftclient/pool.py", "MuxSession", "write_batch"):
        frozenset({"client_api"}),
    # thin-replica commit-listener hop: the ledger's run listeners fire
    # on whichever thread sealed the commit — the execution lane
    # (end_accumulation), the dispatcher (inline execution, ST link
    # segments), or an app thread in unit tests
    ("tpubft/thinreplica/server.py", "ThinReplicaServer", "_on_run"):
        frozenset({"exec_lane", "dispatcher"}),
    # checkpoint-anchor snapshot: served to thin-replica connection
    # handler threads; published by the dispatcher (_store_checkpoint)
    ("tpubft/consensus/replica.py", "Replica", "thin_replica_anchor"):
        frozenset({"thinreplica_srv"}),
    # share-aggregation interior flush (ISSUE 17): the dispatcher's
    # _agg_flush_tick snapshots due buffers and hands this job to
    # CollectorPool.submit as a lambda (callable crossing the pool's
    # executor — invisible to the syntactic call graph, like the
    # _bg_verify_cert hop): it decodes + sums the subtree's shares on a
    # sig-combine worker (one msm_batch launch per flush) and re-enters
    # the dispatcher through push_internal("agg_partial")
    ("tpubft/consensus/replica.py", "Replica", "_agg_combine_job"):
        frozenset({"sig_combine"}),
    # mesh-rebuild path (ISSUE 16): the crypto-mesh manager's plan /
    # eviction state is mutated from every kernel-calling thread (any
    # verify seam can hit on_launch_failure and rebuild the plan) and
    # from the autotuner, whose `crypto_shard_count` knob stores
    # set_shard_count as a callable attribute (Knob.apply_fn) the
    # syntactic call graph cannot see through
    ("tpubft/parallel/sharding.py", "CryptoMesh", "set_shard_count"):
        frozenset({"tuner"}),
    ("tpubft/parallel/sharding.py", "CryptoMesh", "plan"):
        frozenset({"dispatcher", "exec_lane", "admission", "batcher",
                   "sig_combine", "durability"}),
    ("tpubft/parallel/sharding.py", "CryptoMesh", "on_launch_failure"):
        frozenset({"dispatcher", "exec_lane", "admission", "batcher",
                   "sig_combine"}),
}

# -- callback registrars: arg positions/kwargs that receive a function
#    which will run on the named role's thread ------------------------
REGISTRARS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...], str]] = {
    # name -> (positional callback indices, callback kwarg names, role)
    "add_timer": ((1,), ("fn",), "dispatcher"),
    "register_internal": ((1,), ("fn",), "dispatcher"),
    "set_external_handler": ((0,), ("fn",), "dispatcher"),
    "set_admitted_handler": ((0,), ("fn",), "dispatcher"),
    "set_post_hook": ((0,), ("fn",), "dispatcher"),
    "register_probe": ((2, 3, 4), ("busy_fn", "detail_fn", "last_fn"),
                       "health"),
    "register_degraded_flag": ((1,), ("fn",), "health"),
}

# -- type facts the syntactic inference cannot see --------------------
# constructor-injected collaborators: {(rel, Class, attr): (rel, Class)}
ATTR_TYPE_HINTS: Dict[Tuple[str, str, str], Tuple[str, str]] = {
    # the execution lane holds the replica and reaches its thread-safe
    # surfaces (ClientsManager, reserved pages, blockchain accumulation)
    ("tpubft/consensus/execution.py", "ExecutionLane", "_r"):
        ("tpubft/consensus/replica.py", "Replica"),
    # the durability pipeline holds the replica the same way
    ("tpubft/durability/pipeline.py", "DurabilityPipeline", "_r"):
        ("tpubft/consensus/replica.py", "Replica"),
    # admission workers verify through the replica's SigManager and
    # consult the static topology
    ("tpubft/consensus/admission.py", "AdmissionPipeline", "_sig"):
        ("tpubft/consensus/sig_manager.py", "SigManager"),
    ("tpubft/consensus/admission.py", "AdmissionPipeline", "_info"):
        ("tpubft/consensus/replicas_info.py", "ReplicasInfo"),
    # the app handler owns the ledger the exec lane accumulates into
    ("tpubft/apps/skvbc.py", "SkvbcHandler", "blockchain"):
        ("tpubft/kvbc/blockchain.py", "KeyValueBlockchain"),
    ("tpubft/consensus/replica.py", "Replica", "res_pages"):
        ("tpubft/consensus/reserved_pages.py", "ReservedPages"),
}

# factory getters: {fully-dotted function: (rel, Class)} — lets
# `get_breaker(...).record_failure()` chains resolve
RETURN_TYPE_HINTS: Dict[str, Tuple[str, str]] = {
    "tpubft.utils.breaker.get_breaker":
        ("tpubft/utils/breaker.py", "CircuitBreaker"),
    "tpubft.ops.dispatch.device_breaker":
        ("tpubft/utils/breaker.py", "CircuitBreaker"),
    "tpubft.utils.racecheck.get_watchdog":
        ("tpubft/utils/racecheck.py", "StallWatchdog"),
    "tpubft.utils.racecheck.get_checker":
        ("tpubft/utils/racecheck.py", "LockOrderChecker"),
    "tpubft.utils.tracing.get_tracer":
        ("tpubft/utils/tracing.py", "Tracer"),
    # flight recorder: no threads of its own (per-thread rings are
    # written by their OWNING thread; dump artifacts ride the health
    # monitor's already-seeded thread and chaos-campaign callers) —
    # these factories let `slot_tracker().on_event()` /
    # `kernel_profiler().record()` chains resolve so the static-race
    # pass covers the fold/profile state they guard with make_lock
    "tpubft.utils.flight.slot_tracker":
        ("tpubft/utils/flight.py", "SlotTracker"),
    "tpubft.utils.flight.kernel_profiler":
        ("tpubft/utils/flight.py", "KernelProfiler"),
    # crypto-mesh manager (ISSUE 16): lets `crypto_mesh().plan()` /
    # `mesh_manager().on_launch_failure(...)` chains resolve so the
    # static-race pass covers the plan/eviction state guarded by the
    # manager's `crypto_mesh` lock
    "tpubft.parallel.sharding.mesh_manager":
        ("tpubft/parallel/sharding.py", "CryptoMesh"),
    "tpubft.ops.dispatch.crypto_mesh":
        ("tpubft/parallel/sharding.py", "CryptoMesh"),
}

# modules excluded from the concurrency passes (thread-roles,
# static-race, lock-order, dispatcher-blocking): the test/chaos harness
# fakes threads and crash drills by design and is not replica code.
# The legacy passes keep their own historical scopes.
CONCURRENCY_EXCLUDE: Tuple[str, ...] = ("tpubft/testing/",)
