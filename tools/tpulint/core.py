"""tpulint framework core: module loader, findings, baseline.

Shared by every pass (tools/tpulint/passes/): one AST parse per module,
one scan-root convention, one loud zero-scan failure mode (the
tools/check_device_seam.py convention — a wrong root or a package
rename must FAIL, never report a vacuous OK), and one suppression
mechanism (tools/tpulint/baseline.toml: every entry names a pass, a
stable finding key, and a one-line justification; a stale entry — one
that matches no current finding — is itself an error, so the baseline
can only shrink silently, never rot).
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


class ScanError(RuntimeError):
    """Zero modules scanned or an unusable scan root — loud failure."""


@dataclass(frozen=True)
class Finding:
    """One analyzer finding. `key` is the stable baseline handle: it
    deliberately excludes line numbers so an unrelated edit above a
    baselined site does not invalidate the entry."""
    pass_id: str
    path: str                 # repo-relative
    line: int
    key: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


class SourceModule:
    """One parsed module under a scan root."""
    __slots__ = ("rel", "path", "tree")

    def __init__(self, rel: str, path: str, tree: ast.Module) -> None:
        self.rel = rel
        self.path = path
        self.tree = tree


def load_modules(root: str, subdirs: Sequence[str] = ("tpubft",),
                 ) -> Tuple[List[SourceModule], List[Finding]]:
    """Walk `root/<subdir>` for .py files and parse each once. Returns
    (modules, syntax-error findings). Zero parseable files raises
    ScanError — the enforced-by-construction properties downstream
    would silently stop being enforced on a vacuous scan."""
    mods: List[SourceModule] = []
    findings: List[Finding] = []
    scanned = 0
    for sub in subdirs:
        top = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                scanned += 1
                with open(path, "rb") as f:
                    try:
                        tree = ast.parse(f.read(), filename=path)
                    except SyntaxError as e:
                        findings.append(Finding(
                            "loader", rel, e.lineno or 0,
                            f"syntax:{rel}", f"syntax error: {e.msg}"))
                        continue
                mods.append(SourceModule(rel, path, tree))
    if not scanned:
        raise ScanError(
            f"no Python modules found under {root} (subdirs: "
            f"{','.join(subdirs)}) — wrong root? A zero-module scan "
            f"must fail, not report a vacuous OK")
    return mods, findings


# ----------------------------------------------------------------------
# baseline (suppression) file
# ----------------------------------------------------------------------

@dataclass
class BaselineEntry:
    pass_id: str
    key: str
    reason: str
    line: int
    used: bool = field(default=False, compare=False)


class BaselineError(RuntimeError):
    """Malformed baseline file — fail loudly, never half-apply."""


def _toml_string(raw: str, path: str, lineno: int) -> str:
    raw = raw.strip()
    if len(raw) < 2 or raw[0] != '"' or raw[-1] != '"':
        raise BaselineError(
            f"{path}:{lineno}: value must be a double-quoted string")
    body = raw[1:-1]
    if '"' in body.replace('\\"', ""):
        raise BaselineError(
            f"{path}:{lineno}: unescaped quote inside string")
    return body.replace('\\"', '"').replace("\\\\", "\\")


def parse_baseline(path: str) -> List[BaselineEntry]:
    """Minimal TOML-subset reader for baseline.toml (Python 3.10 has no
    tomllib): `[[suppress]]` array-of-tables with `pass` / `key` /
    `reason` basic-string fields and `#` comments. Anything else is a
    BaselineError — a suppression file must never be half-understood."""
    entries: List[BaselineEntry] = []
    cur: Optional[Dict[str, object]] = None

    def flush() -> None:
        nonlocal cur
        if cur is None:
            return
        for fld in ("pass", "key", "reason"):
            if fld not in cur:
                raise BaselineError(
                    f"{path}:{cur['line']}: suppress entry missing "
                    f"required field {fld!r}")
        if not str(cur["reason"]).strip():
            raise BaselineError(
                f"{path}:{cur['line']}: empty `reason` — every baseline "
                f"entry needs a one-line justification")
        entries.append(BaselineEntry(str(cur["pass"]), str(cur["key"]),
                                     str(cur["reason"]), int(cur["line"])))  # type: ignore[arg-type]
        cur = None

    with open(path, "r", encoding="utf-8") as f:
        for lineno, raw_line in enumerate(f, 1):
            line = raw_line.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[suppress]]":
                flush()
                cur = {"line": lineno}
                continue
            if "=" in line and cur is not None:
                name, _, value = line.partition("=")
                name = name.strip()
                if name not in ("pass", "key", "reason"):
                    raise BaselineError(
                        f"{path}:{lineno}: unknown field {name!r} "
                        f"(allowed: pass, key, reason)")
                # strip a trailing comment outside the string
                value = value.strip()
                if value.count('"') >= 2:
                    end = value.rfind('"')
                    value = value[:end + 1]
                cur[name] = _toml_string(value, path, lineno)
                continue
            raise BaselineError(
                f"{path}:{lineno}: unparseable line {line!r} (expected "
                f"[[suppress]] tables with pass/key/reason strings)")
    flush()
    return entries


def apply_baseline(findings: List[Finding], entries: List[BaselineEntry],
                   known_passes: Sequence[str],
                   baseline_rel: str) -> Tuple[List[Finding], int,
                                               List[Finding]]:
    """Split findings into (kept, n_suppressed, baseline_errors).
    Baseline errors — an entry naming an unknown pass, a duplicate
    (pass, key), or a stale entry matching no current finding — are
    findings themselves: an unknown suppression key must fail loudly,
    not silently suppress nothing."""
    errors: List[Finding] = []
    seen: Dict[Tuple[str, str], BaselineEntry] = {}
    for e in entries:
        if e.pass_id not in known_passes:
            errors.append(Finding(
                "baseline", baseline_rel, e.line,
                f"unknown-pass:{e.pass_id}",
                f"baseline entry names unknown pass {e.pass_id!r} "
                f"(known: {', '.join(known_passes)})"))
            continue
        dup = seen.get((e.pass_id, e.key))
        if dup is not None:
            errors.append(Finding(
                "baseline", baseline_rel, e.line,
                f"dup:{e.pass_id}:{e.key}",
                f"duplicate baseline entry for [{e.pass_id}] {e.key!r} "
                f"(first at line {dup.line})"))
            continue
        seen[(e.pass_id, e.key)] = e
    kept: List[Finding] = []
    n_suppressed = 0
    for f in findings:
        e = seen.get((f.pass_id, f.key))
        if e is not None:
            e.used = True
            n_suppressed += 1
        else:
            kept.append(f)
    for e in seen.values():
        if not e.used:
            errors.append(Finding(
                "baseline", baseline_rel, e.line,
                f"stale:{e.pass_id}:{e.key}",
                f"stale baseline entry: [{e.pass_id}] {e.key!r} matches "
                f"no current finding — remove it (fixed findings must "
                f"not leave dead suppressions behind)"))
    return kept, n_suppressed, errors
