import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.tpulint import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
