"""Whole-program AST index over the tpubft tree.

Builds, from the shared loader's parsed modules, the structures every
concurrency pass consumes:

  * per-module import tables (alias -> dotted target) and symbol tables
    (classes, module-level functions, module-level locks);
  * per-class method tables, base-class links (resolved within the
    repo), attribute types inferred from `self.x = ClassName(...)`
    assignments, and lock attributes with their provenance
    (racecheck.make_lock / make_condition vs raw threading primitives,
    plus Conditions layered over another lock attribute);
  * a conservative syntactic call graph: `f()`, `mod.f()`, `self.m()`,
    `self.attr.m()` and `local_var.m()` (where the attr/var type was
    inferred), `ClassName(...)` -> `__init__`, and `lambda: <call>`
    thunks.

The graph is deliberately under-approximate where Python's dynamism
gives no static answer (callables stored in attributes, dict dispatch):
those edges are restored by the role seed table
(tools/tpulint/rolemap.py) and the callback-registrar rules in the
thread-role pass, which is how the framework stays precise enough to
lint a real tree without drowning it in false positives.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.tpulint.core import SourceModule

FuncId = Tuple[str, Optional[str], str]   # (module rel, class or None, name)


def fid_key(fid: FuncId) -> Tuple[str, str, str]:
    """Sort key for FuncIds (class may be None)."""
    return (fid[0], fid[1] or "", fid[2])

# lock provenance kinds
MAKE_LOCK = "make_lock"
MAKE_CONDITION = "make_condition"
RAW_LOCK = "raw_lock"
RAW_CONDITION = "raw_condition"

_LOCK_FACTORIES = {
    "tpubft.utils.racecheck.make_lock": MAKE_LOCK,
    "tpubft.utils.racecheck.CheckedLock": MAKE_LOCK,
    "tpubft.utils.racecheck.make_condition": MAKE_CONDITION,
    "tpubft.utils.racecheck.CheckedCondition": MAKE_CONDITION,
    "threading.Lock": RAW_LOCK,
    "threading.RLock": RAW_LOCK,
    "threading.Condition": RAW_CONDITION,
}


class LockInfo:
    """One lock-valued attribute (or module global). `underlying` names
    the lock attr a Condition wraps, so `with self._cond:` and
    `with self._mu:` unify to one node in the order graph."""
    __slots__ = ("owner", "attr", "kind", "line", "underlying")

    def __init__(self, owner: str, attr: str, kind: str, line: int,
                 underlying: Optional[str] = None) -> None:
        self.owner = owner            # "ClassName" or "module:<rel>"
        self.attr = attr
        self.kind = kind
        self.line = line
        self.underlying = underlying

    @property
    def lock_id(self) -> str:
        return f"{self.owner}.{self.underlying or self.attr}"

    @property
    def registered(self) -> bool:
        return self.kind in (MAKE_LOCK, MAKE_CONDITION)


class FuncInfo:
    __slots__ = ("id", "module", "cls", "name", "node", "nested")

    def __init__(self, module: str, cls: Optional[str], name: str,
                 node: ast.AST) -> None:
        self.id: FuncId = (module, cls, name)
        self.module = module
        self.cls = cls
        self.name = name
        self.node = node
        # closures defined directly inside this function, by bare name
        # (their FuncId name is "outer.inner")
        self.nested: Dict[str, "FuncInfo"] = {}

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class ClassInfo:
    __slots__ = ("module", "name", "bases", "methods", "attr_types",
                 "locks", "node")

    def __init__(self, module: str, name: str, node: ast.ClassDef) -> None:
        self.module = module
        self.name = name
        self.node = node
        self.bases: List[str] = []            # dotted base names, raw
        self.methods: Dict[str, FuncInfo] = {}
        self.attr_types: Dict[str, "ClassInfo"] = {}
        self.locks: Dict[str, LockInfo] = {}


class ModuleInfo:
    __slots__ = ("rel", "dotted", "tree", "imports", "classes",
                 "functions", "locks")

    def __init__(self, rel: str, dotted: str, tree: ast.Module) -> None:
        self.rel = rel
        self.dotted = dotted
        self.tree = tree
        self.imports: Dict[str, str] = {}     # local alias -> dotted
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.locks: Dict[str, LockInfo] = {}  # module-level lock vars


def _dotted_of(rel: str) -> str:
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("\\", "/").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def dotted_expr(node: ast.AST) -> Optional[str]:
    """`a.b.c` chain as a string, or None for anything non-trivial."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_body(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk limited to one function's own body: nested function /
    lambda / class subtrees are skipped (their statements execute when
    *they* run, on whatever thread calls them — the call graph and the
    role map carry that, not lexical position)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.ClassDef)):
            continue
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


class Program:
    def __init__(self, modules: Sequence[SourceModule],
                 attr_hints=None, return_hints=None) -> None:
        """`attr_hints`: {(rel, Class, attr): (rel, Class)} type facts
        for constructor-injected collaborators the syntactic inference
        cannot see. `return_hints`: {fully-dotted function: (rel,
        Class)} for factory getters (`get_breaker(...)` etc.)."""
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_dotted: Dict[str, ModuleInfo] = {}
        self.funcs: Dict[FuncId, FuncInfo] = {}
        self._class_by_name: Dict[str, List[ClassInfo]] = {}
        self._local_types_cache: Dict[FuncId, Dict[str, ClassInfo]] = {}
        self._callees_cache: Dict[FuncId, List[Tuple[FuncInfo, int]]] = {}
        self._subclasses: Optional[Dict] = None
        for sm in modules:
            self._index_module(sm)
        for mi in self.modules.values():
            self._link_module(mi)
        self._returns: Dict[str, ClassInfo] = {}
        for dotted, (rel, cls) in (return_hints or {}).items():
            ci = self._class_at(rel, cls)
            if ci is not None:
                self._returns[dotted] = ci
        for (rel, cls, attr), (trel, tcls) in (attr_hints or {}).items():
            owner = self._class_at(rel, cls)
            target = self._class_at(trel, tcls)
            if owner is not None and target is not None:
                owner.attr_types.setdefault(attr, target)

    def _class_at(self, rel: str, cls: str) -> Optional["ClassInfo"]:
        mi = self.modules.get(rel)
        return mi.classes.get(cls) if mi is not None else None

    def subclasses(self, ci: ClassInfo) -> List[ClassInfo]:
        """Transitive repo subclasses of `ci`."""
        if self._subclasses is None:
            direct: Dict[Tuple[str, str], List[ClassInfo]] = {}
            for mi in self.modules.values():
                for c in mi.classes.values():
                    for b in c.bases:
                        base = self.resolve_class(mi, b)
                        if base is not None:
                            direct.setdefault(
                                (base.module, base.name), []).append(c)
            self._subclasses = direct
        out: List[ClassInfo] = []
        seen: Set[Tuple[str, str]] = set()
        stack = [ci]
        while stack:
            cur = stack.pop()
            for sub in self._subclasses.get((cur.module, cur.name), ()):
                key = (sub.module, sub.name)
                if key not in seen:
                    seen.add(key)
                    out.append(sub)
                    stack.append(sub)
        return out

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def _index_module(self, sm: SourceModule) -> None:
        mi = ModuleInfo(sm.rel, _dotted_of(sm.rel), sm.tree)
        self.modules[sm.rel] = mi
        self.by_dotted[mi.dotted] = mi
        pkg = mi.dotted.rsplit(".", 1)[0] if "." in mi.dotted else ""
        for node in ast.walk(sm.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    mi.imports[alias] = (a.name if a.asname
                                         else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    steps = mi.dotted.split(".")
                    # level 1 = current package (module's own parent)
                    anchor = steps[: len(steps) - node.level] or [""]
                    base = ".".join(x for x in (".".join(anchor), base) if x)
                for a in node.names:
                    if a.name == "*":
                        continue
                    alias = a.asname or a.name
                    mi.imports[alias] = f"{base}.{a.name}" if base else a.name
        del pkg
        for stmt in sm.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(sm.rel, None, stmt.name, stmt)
                mi.functions[stmt.name] = fi
                self.funcs[fi.id] = fi
                self._index_nested(fi)
            elif isinstance(stmt, ast.ClassDef):
                ci = ClassInfo(sm.rel, stmt.name, stmt)
                for b in stmt.bases:
                    d = dotted_expr(b)
                    if d:
                        ci.bases.append(d)
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = FuncInfo(sm.rel, stmt.name, item.name, item)
                        ci.methods[item.name] = fi
                        self.funcs[fi.id] = fi
                        self._index_nested(fi)
                mi.classes[stmt.name] = ci
                self._class_by_name.setdefault(stmt.name, []).append(ci)

    def _index_nested(self, outer: FuncInfo) -> None:
        for child in ast.iter_child_nodes(outer.node):
            self._collect_nested(outer, child)

    def _collect_nested(self, outer: FuncInfo, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fi = FuncInfo(outer.module, outer.cls,
                          f"{outer.name}.{node.name}", node)
            outer.nested[node.name] = fi
            self.funcs[fi.id] = fi
            for child in ast.iter_child_nodes(node):
                self._collect_nested(fi, child)
            return
        if isinstance(node, (ast.Lambda, ast.ClassDef)):
            return
        for child in ast.iter_child_nodes(node):
            self._collect_nested(outer, child)

    def _factory_kind(self, mi: ModuleInfo, call: ast.Call) -> Optional[str]:
        d = dotted_expr(call.func)
        if d is None:
            return None
        target = self.resolve_dotted(mi, d)
        return _LOCK_FACTORIES.get(target or "")

    def _lock_from_assign(self, mi: ModuleInfo, owner: str, attr: str,
                          value: ast.expr, line: int,
                          locks: Dict[str, LockInfo]) -> Optional[LockInfo]:
        if not isinstance(value, ast.Call):
            return None
        kind = self._factory_kind(mi, value)
        if kind is None:
            return None
        if kind == RAW_CONDITION and value.args:
            arg = value.args[0]
            # Condition(self._mu) / Condition(make_lock(...)): inherit
            # the wrapped lock's provenance and identity
            if isinstance(arg, ast.Attribute) \
                    and isinstance(arg.value, ast.Name) \
                    and arg.value.id == "self":
                under = locks.get(arg.attr)
                if under is not None:
                    return LockInfo(owner, attr, under.kind, line,
                                    underlying=under.attr)
            elif isinstance(arg, ast.Call):
                inner = self._factory_kind(mi, arg)
                if inner in (MAKE_LOCK, MAKE_CONDITION):
                    return LockInfo(owner, attr, inner, line)
                if inner in (RAW_LOCK, RAW_CONDITION):
                    return LockInfo(owner, attr, RAW_CONDITION, line)
        return LockInfo(owner, attr, kind, line)

    def _link_module(self, mi: ModuleInfo) -> None:
        # module-level locks
        for stmt in mi.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                li = self._lock_from_assign(mi, f"module:{mi.rel}", name,
                                            stmt.value, stmt.lineno,
                                            mi.locks)
                if li is not None:
                    mi.locks[name] = li
        # class attr types + lock attrs (two passes over every method so
        # `self._cond = Condition(self._mu)` sees `_mu` regardless of
        # statement order)
        for ci in mi.classes.values():
            assigns: List[Tuple[str, ast.expr, int, Dict]] = []
            for fn in ci.methods.values():
                params = self._param_types(mi, fn)
                for node in ast.walk(fn.node):
                    target: Optional[ast.expr] = None
                    if isinstance(node, ast.Assign) \
                            and len(node.targets) == 1:
                        target = node.targets[0]
                    elif isinstance(node, ast.AnnAssign) and node.value:
                        target = node.target
                    if target is None or node.value is None:
                        continue
                    if isinstance(target, ast.Attribute) \
                            and isinstance(target.value, ast.Name) \
                            and target.value.id == "self":
                        assigns.append((target.attr, node.value,
                                        node.lineno, params))
            for attr, value, line, _p in assigns:    # plain locks first
                if isinstance(value, ast.Call):
                    kind = self._factory_kind(mi, value)
                    if kind in (MAKE_LOCK, MAKE_CONDITION, RAW_LOCK):
                        ci.locks[attr] = LockInfo(ci.name, attr, kind,
                                                  line)
            for attr, value, line, _p in assigns:    # then conditions
                if attr in ci.locks:
                    continue
                li = self._lock_from_assign(mi, ci.name, attr, value,
                                            line, ci.locks)
                if li is not None:
                    ci.locks[attr] = li
            for attr, value, line, params in assigns:  # then obj types
                if attr in ci.locks or attr in ci.attr_types:
                    continue
                hit = None
                if isinstance(value, ast.Call):
                    target = dotted_expr(value.func)
                    if target:
                        hit = self.resolve_class(mi, target)
                elif isinstance(value, ast.Name):
                    # self._bc = blockchain  (annotated parameter)
                    hit = params.get(value.id)
                if hit is not None:
                    ci.attr_types[attr] = hit
            # properties returning a typed attribute: handler.blockchain
            for name, fn in ci.methods.items():
                if name in ci.attr_types or not isinstance(
                        fn.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if not any(isinstance(d, ast.Name) and d.id == "property"
                           for d in fn.node.decorator_list):
                    continue
                for node in walk_body(fn.node):
                    if isinstance(node, ast.Return) \
                            and isinstance(node.value, ast.Attribute) \
                            and isinstance(node.value.value, ast.Name) \
                            and node.value.value.id == "self":
                        hit = ci.attr_types.get(node.value.attr)
                        if hit is not None:
                            ci.attr_types[name] = hit
                        break

    def _param_types(self, mi: ModuleInfo, fn: FuncInfo
                     ) -> Dict[str, "ClassInfo"]:
        """Annotated parameters whose annotation names a repo class."""
        out: Dict[str, ClassInfo] = {}
        node = fn.node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return out
        for arg in (node.args.posonlyargs + node.args.args
                    + node.args.kwonlyargs):
            ann = arg.annotation
            if ann is None:
                continue
            d = dotted_expr(ann)
            if isinstance(ann, ast.Constant) and isinstance(ann.value,
                                                            str):
                d = ann.value
            if d:
                hit = self.resolve_class(mi, d)
                if hit is not None:
                    out[arg.arg] = hit
        return out

    # ------------------------------------------------------------------
    # resolution
    # ------------------------------------------------------------------
    def resolve_dotted(self, mi: ModuleInfo, dotted: str) -> Optional[str]:
        """Expand a local dotted name through the module's imports into a
        fully-qualified dotted path (repo or external)."""
        head, _, rest = dotted.partition(".")
        if head in mi.imports:
            base = mi.imports[head]
            return f"{base}.{rest}" if rest else base
        if head in mi.classes or head in mi.functions or head in mi.locks:
            return f"{mi.dotted}.{dotted}"
        return dotted

    def resolve_class(self, mi: ModuleInfo,
                      dotted: str) -> Optional[ClassInfo]:
        full = self.resolve_dotted(mi, dotted)
        if full is None:
            return None
        mod_path, _, name = full.rpartition(".")
        owner = self.by_dotted.get(mod_path)
        if owner is not None and name in owner.classes:
            return owner.classes[name]
        # unique global name as a fallback (covers re-exports)
        if "." not in dotted:
            cands = self._class_by_name.get(dotted, [])
            if len(cands) == 1:
                return cands[0]
        return None

    def mro(self, ci: ClassInfo) -> List[ClassInfo]:
        out, stack, seen = [], [ci], set()
        while stack:
            cur = stack.pop(0)
            if id(cur) in seen:
                continue
            seen.add(id(cur))
            out.append(cur)
            mi = self.modules[cur.module]
            for b in cur.bases:
                hit = self.resolve_class(mi, b)
                if hit is not None:
                    stack.append(hit)
        return out

    def lookup_method(self, ci: ClassInfo,
                      name: str) -> Optional[FuncInfo]:
        for c in self.mro(ci):
            if name in c.methods:
                return c.methods[name]
        return None

    def class_lock(self, ci: ClassInfo, attr: str) -> Optional[LockInfo]:
        for c in self.mro(ci):
            if attr in c.locks:
                return c.locks[attr]
        return None

    def _local_types(self, fi: FuncInfo) -> Dict[str, ClassInfo]:
        """var -> ClassInfo for `x = ClassName(...)` and `x = self.attr`
        assignments inside one function body."""
        cached = self._local_types_cache.get(fi.id)
        if cached is not None:
            return cached
        assigns = [n for n in walk_body(fi.node)
                   if isinstance(n, ast.Assign) and len(n.targets) == 1
                   and isinstance(n.targets[0], ast.Name)]
        out: Dict[str, ClassInfo] = dict(
            self._param_types(self.modules[fi.module], fi))
        # iterate to a small fixpoint: walk order is not source order,
        # and chains like `r = self._r; bc = r.handler.blockchain` need
        # the earlier binding resolved first
        for _ in range(4):
            changed = False
            for node in assigns:
                var = node.targets[0].id
                if var in out:
                    continue
                hit = self.expr_type(fi, node.value, out)
                if hit is not None:
                    out[var] = hit
                    changed = True
            if not changed:
                break
        self._local_types_cache[fi.id] = out
        return out

    def expr_type(self, fi: FuncInfo, node: ast.AST,
                  local_types: Dict[str, ClassInfo]
                  ) -> Optional[ClassInfo]:
        """Best-effort static type of an expression: `self`, typed
        locals, attribute chains through inferred/hinted attr types,
        constructor calls, factory-getter returns, and literal-name
        `getattr(x, "attr")`."""
        mi = self.modules[fi.module]
        if isinstance(node, ast.Name):
            if node.id == "self" and fi.cls:
                return mi.classes.get(fi.cls)
            return local_types.get(node.id)
        if isinstance(node, ast.Attribute):
            base_t = self.expr_type(fi, node.value, local_types)
            if base_t is not None:
                return self._attr_type_of(base_t, node.attr)
            return None
        if isinstance(node, ast.Call):
            d = dotted_expr(node.func)
            if d == "getattr" and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                base_t = self.expr_type(fi, node.args[0], local_types)
                if base_t is not None:
                    return self._attr_type_of(base_t, node.args[1].value)
                return None
            if d:
                hit = self.resolve_class(mi, d)
                if hit is not None:
                    return hit
                return self._returns.get(self.resolve_dotted(mi, d) or "")
        return None

    def _attr_type_of(self, owner: ClassInfo,
                      attr: str) -> Optional[ClassInfo]:
        """Type of `owner.<attr>`, searching the MRO and — when the
        static type is an interface — its repo subclasses (sound for
        typing: any implementation the attr may come from)."""
        for c in self.mro(owner):
            if attr in c.attr_types:
                return c.attr_types[attr]
        for sub in self.subclasses(owner):
            if attr in sub.attr_types:
                return sub.attr_types[attr]
        return None

    def resolve_func_ref(self, fi: FuncInfo, node: ast.AST,
                         local_types: Optional[Dict[str, ClassInfo]] = None
                         ) -> List[FuncInfo]:
        """Resolve a *function-valued expression* (callee of a call, or a
        callback argument) to repo FuncInfos. Under-approximate."""
        mi = self.modules[fi.module]
        if local_types is None:
            local_types = self._local_types(fi)
        if isinstance(node, ast.Lambda):
            body = node.body
            if isinstance(body, ast.Call):
                return self.resolve_func_ref(fi, body.func, local_types)
            return []
        if isinstance(node, ast.Name):
            if node.id in fi.nested:          # closure defined right here
                return [fi.nested[node.id]]
            if node.id in local_types:        # x = ClassName(...); x(...)
                hit = self.lookup_method(local_types[node.id], "__call__")
                return [hit] if hit else []
            full = self.resolve_dotted(mi, node.id)
            return self._by_dotted_func(full)
        if isinstance(node, ast.Attribute):
            owner = self.expr_type(fi, node.value, local_types)
            if owner is not None:
                hits = []
                hit = self.lookup_method(owner, node.attr)
                if hit is not None:
                    hits.append(hit)
                # the static type may be an interface: include every
                # override in repo subclasses (conservative dispatch)
                for sub in self.subclasses(owner):
                    if node.attr in sub.methods:
                        hits.append(sub.methods[node.attr])
                return hits
            d = dotted_expr(node)
            if d:
                return self._by_dotted_func(self.resolve_dotted(mi, d))
        return []

    def _by_dotted_func(self, full: Optional[str]) -> List[FuncInfo]:
        if not full:
            return []
        mod_path, _, name = full.rpartition(".")
        owner = self.by_dotted.get(mod_path)
        if owner is None:
            return []
        if name in owner.functions:
            return [owner.functions[name]]
        if name in owner.classes:
            hit = self.lookup_method(owner.classes[name], "__init__")
            return [hit] if hit else []
        return []

    def callees(self, fi: FuncInfo) -> List[Tuple[FuncInfo, int]]:
        """Resolved (callee, lineno) pairs for every call in `fi`'s own
        body (nested defs excluded — they are their own nodes)."""
        cached = self._callees_cache.get(fi.id)
        if cached is not None:
            return cached
        local_types = self._local_types(fi)
        out: List[Tuple[FuncInfo, int]] = []
        for node in walk_body(fi.node):
            if isinstance(node, ast.Call):
                for hit in self.resolve_func_ref(fi, node.func,
                                                 local_types):
                    out.append((hit, node.lineno))
        self._callees_cache[fi.id] = out
        return out
