"""Pass: crashpoint agreement lint (migrated from
tools/check_crashpoints.py).

The recovery drills address durability seams BY NAME; the scheme decays
silently if names drift. Enforced: every `crashpoint(...)`/`arm(...)`
name (and every TPUBFT_CRASHPOINT env literal) is registered in
crashpoints.REGISTRY; every REGISTRY entry is threaded at ≥1 real seam
outside the harness; zero scanned modules fails loudly.
tools/check_crashpoints.py remains the CLI shim.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

from tools.tpulint.core import Finding, ScanError, load_modules

PASS_ID = "crashpoints"

Violation = Tuple[str, int, str]

HOOK_FUNCS = {"crashpoint", "arm"}
SCAN_DIRS = ("tpubft", "benchmarks", "tests")
# seams live in production code: registry coverage is only satisfied by
# a call site outside the harness itself
HARNESS_PREFIXES = (os.path.join("tpubft", "testing") + os.sep,
                    "benchmarks" + os.sep, "tests" + os.sep)


def _literal_name(node: ast.Call) -> Tuple[bool, str]:
    """(is_literal, value) of the call's first positional arg / name=."""
    arg = node.args[0] if node.args else next(
        (kw.value for kw in node.keywords if kw.arg == "name"), None)
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return True, arg.value
    return False, ""


def _env_names(node: ast.AST) -> List[str]:
    """Crashpoint names inside string literals shaped like env specs:
    {"TPUBFT_CRASHPOINT": "name[:hit]"} dict displays."""
    names: List[str] = []
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            key = getattr(k, "value", None)
            is_env_key = key == "TPUBFT_CRASHPOINT" or (
                isinstance(k, ast.Name) and k.id == "ENV_VAR")
            if is_env_key and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                names.append(v.value.partition(":")[0])
    return names


def _scan_tree(tree: ast.Module, rel: str, registry: Set[str],
               seams: Dict[str, int]) -> List[Violation]:
    out: List[Violation] = []
    in_harness = rel.startswith(HARNESS_PREFIXES)
    for node in ast.walk(tree):
        for name in _env_names(node):
            if name not in registry:
                out.append((rel, node.lineno,
                            f"TPUBFT_CRASHPOINT={name!r} names an "
                            f"unregistered crashpoint"))
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        called = (fn.id if isinstance(fn, ast.Name)
                  else fn.attr if isinstance(fn, ast.Attribute) else None)
        if called not in HOOK_FUNCS:
            continue
        is_lit, name = _literal_name(node)
        if not is_lit:
            # registry.REGISTRY-driven loops (the lint's own tests, a
            # drill iterating all seams) are fine for arm(); a seam
            # itself must be a greppable literal
            if called == "crashpoint":
                out.append((rel, node.lineno,
                            "crashpoint() seam name must be a string "
                            "literal (drills address seams by grep)"))
            continue
        if name not in registry:
            out.append((rel, node.lineno,
                        f"{called}({name!r}) references an unregistered "
                        f"crashpoint (add it to crashpoints.REGISTRY)"))
        elif called == "crashpoint" and not in_harness \
                and rel != os.path.join("tpubft", "testing",
                                        "crashpoints.py"):
            seams[name] = seams.get(name, 0) + 1
    return out


def _load_registry(root: str) -> Tuple[Set[str], List[Violation]]:
    """REGISTRY keys, AST-parsed from the root's own crashpoints.py (no
    import: the module under test must be the one under `root`, not
    whatever sys.modules cached)."""
    rel = os.path.join("tpubft", "testing", "crashpoints.py")
    path = os.path.join(root, rel)
    if not os.path.exists(path):
        return set(), [(rel, 0, "crashpoints.py not found — wrong root?")]
    with open(path, "rb") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign) or isinstance(node, ast.Assign):
            targets = ([node.target] if isinstance(node, ast.AnnAssign)
                       else node.targets)
            if any(isinstance(t, ast.Name) and t.id == "REGISTRY"
                   for t in targets) and isinstance(node.value, ast.Dict):
                keys = [k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)]
                return set(keys), []
    return set(), [(rel, 0, "REGISTRY dict literal not found")]


def violations_for(root: str, mods, syntax) -> List[Violation]:
    registry, out = _load_registry(root)
    if out:
        return out
    seams: Dict[str, int] = {}
    if not mods and not syntax:
        # a wrong root must FAIL, not report a vacuous OK
        return [(root, 0, "no Python modules found to scan — wrong "
                          "root? (expected <root>/{%s}/**/*.py)"
                          % ",".join(SCAN_DIRS))]
    for f in syntax:
        out.append((f.path, f.line, f.message))
    for sm in mods:
        out.extend(_scan_tree(sm.tree, sm.rel, registry, seams))
    for name in sorted(registry - set(seams)):
        out.append((os.path.join("tpubft", "testing", "crashpoints.py"), 0,
                    f"REGISTRY entry {name!r} is not threaded at any "
                    f"durability seam (phantom coverage — remove it or "
                    f"add the crashpoint() call)"))
    if not seams:
        out.append((root, 0, "zero crashpoint seams found outside the "
                             "harness — the recovery drills cover "
                             "nothing"))
    return sorted(out)


def find_violations(root: str) -> List[Violation]:
    try:
        mods, syntax = load_modules(root, SCAN_DIRS)
    except ScanError:
        mods, syntax = [], []
    return violations_for(root, mods, syntax)


def run(ctx) -> List[Finding]:
    # per-subdir loads so the tpubft/ parse is shared with every other
    # pass through the Context cache; an individual empty subdir is
    # fine, ALL empty is the loud zero-scan
    mods, syntax = [], []
    for sub in SCAN_DIRS:
        try:
            m, s = ctx.load(sub)
        except ScanError:
            continue
        mods.extend(m)
        syntax.extend(s)
    findings: List[Finding] = []
    for rel, line, msg in violations_for(ctx.root, mods, syntax):
        findings.append(Finding(PASS_ID, rel, line, f"{rel}:{msg[:60]}",
                                msg))
    return findings
