"""Pass: import hygiene (migrated from tools/check_imports.py).

No module-level third-party imports under tpubft/: the product tree
must import cleanly in a bare environment (the seed regression was a
module-level `import cryptography` breaking collection of 32/51 test
modules). Module-level means executed at import time — anything
outside a function/class body and outside a `try:` soft-import guard.
Approved always-present deps (`jax`, `numpy`) and the repo's own
packages are allowed. tools/check_imports.py remains the CLI shim.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Iterator, List, Tuple

from tools.tpulint.core import Finding, ScanError, load_modules

PASS_ID = "imports"

APPROVED = {"jax", "numpy"}
INTERNAL = {"tpubft", "tests", "tools", "benchmarks"}


def _stdlib_names() -> frozenset:
    return frozenset(sys.stdlib_module_names)  # 3.10+


def _is_type_checking_test(test: ast.expr) -> bool:
    """`if TYPE_CHECKING:` / `if typing.TYPE_CHECKING:` bodies never
    execute at runtime — imports there are annotations-only, not a
    collection-time dependency."""
    if isinstance(test, ast.Name):
        return test.id == "TYPE_CHECKING"
    if isinstance(test, ast.Attribute):
        return test.attr == "TYPE_CHECKING"
    return False


def _top_level_import_nodes(tree: ast.Module) -> Iterator[ast.stmt]:
    """Statements executed at import time: the module body plus every
    compound-statement body that runs during import — `if`/`else` (a
    version gate still executes), `for`/`while` (+else), `with`, and a
    `try`'s else/finally. EXCLUDED: `try:` bodies and their handlers
    (try/except ImportError is the sanctioned soft-import idiom),
    function/class bodies (lazy imports), and `if TYPE_CHECKING:`
    (never executes)."""
    stack: List[ast.stmt] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, ast.If):
            if not _is_type_checking_test(node.test):
                stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, (ast.For, ast.While)):
            stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, ast.With):
            stack.extend(node.body)
        elif isinstance(node, ast.Try):
            stack.extend(node.orelse)
            stack.extend(node.finalbody)


def _imported_roots(node: ast.stmt) -> Iterator[Tuple[str, int]]:
    if isinstance(node, ast.Import):
        for alias in node.names:
            yield alias.name.split(".")[0], node.lineno
    elif isinstance(node, ast.ImportFrom):
        if node.level:                       # relative import: internal
            return
        if node.module:
            yield node.module.split(".")[0], node.lineno


def scan_tree(tree: ast.Module, approved=None,
              internal=None) -> List[Tuple[int, str]]:
    """(lineno, offending module) pairs for one parsed module."""
    stdlib = _stdlib_names()
    approved = APPROVED if approved is None else approved
    internal = INTERNAL if internal is None else internal
    out: List[Tuple[int, str]] = []
    for node in _top_level_import_nodes(tree):
        for mod, lineno in _imported_roots(node):
            if mod in stdlib or mod in approved or mod in internal:
                continue
            out.append((lineno, mod))
    return out


def find_violations(root: str, approved=None,
                    internal=None) -> List[Tuple[int, int, str]]:
    """Walk `root` for .py files; return (path, lineno, module) for each
    module-level import of a non-stdlib, non-approved package. (The
    historical check_imports API: paths are root-joined, an empty tree
    is an empty report — the framework `run` adds the loud zero-scan.)"""
    try:
        mods, syntax = load_modules(root, ("",))
    except ScanError:
        return []
    out: List[Tuple[str, int, str]] = []
    for f in syntax:
        out.append((os.path.join(root, f.path), f.line,
                    f"<{f.message}>"))
    for sm in mods:
        for lineno, mod in scan_tree(sm.tree, approved, internal):
            out.append((sm.path, lineno, mod))
    return sorted(out)


def run(ctx) -> List[Finding]:
    mods, syntax = ctx.load("tpubft")       # loud on zero scan
    findings = list(syntax)
    for sm in mods:
        for lineno, mod in scan_tree(sm.tree):
            findings.append(Finding(
                PASS_ID, sm.rel, lineno, f"{sm.rel}:{mod}",
                f"module-level import of third-party package {mod!r} "
                f"(use a function-level or try-guarded import; approved "
                f"always-on deps: {sorted(APPROVED)})"))
    return findings
