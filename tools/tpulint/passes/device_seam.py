"""Pass: device-seam lint (migrated from tools/check_device_seam.py).

Every kernel call site goes through the breaker-guarded
`device_section(kind)` seam: any reference to the raw `device_dispatch`
gate — import, call, or attribute — outside tpubft/ops/dispatch.py
bypasses failure classification, the OPEN fast-fail, and half-open
probe accounting. tools/check_device_seam.py remains the CLI shim.
"""
from __future__ import annotations

import ast
import os
from typing import List, Tuple

from tools.tpulint.core import Finding, ScanError, load_modules

PASS_ID = "device-seam"

FORBIDDEN = "device_dispatch"
# the one module allowed to touch the raw gate (it defines it and wraps
# it in the breaker-guarded device_section)
ALLOWED = {os.path.join("tpubft", "ops", "dispatch.py")}


def scan_tree(tree: ast.Module, rel: str,
              forbidden: str = FORBIDDEN) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(tree):
        hit = None
        if isinstance(node, ast.Name) and node.id == forbidden:
            hit = f"references {forbidden}"
        elif isinstance(node, ast.Attribute) and node.attr == forbidden:
            hit = f"references .{forbidden}"
        elif isinstance(node, ast.ImportFrom) \
                and any(a.name == forbidden for a in node.names):
            hit = f"imports {forbidden}"
        if hit:
            out.append((rel, node.lineno,
                        f"{hit} — kernel call sites must use the "
                        f"breaker-guarded device_section(kind) seam "
                        f"(tpubft/ops/dispatch.py)"))
    return out


def violations_for(mods, syntax, forbidden: str = FORBIDDEN,
                   allowed=None) -> List[Tuple[str, int, str]]:
    allowed = ALLOWED if allowed is None else allowed
    out: List[Tuple[str, int, str]] = []
    for f in syntax:
        out.append((f.path, f.line, f.message))
    for sm in mods:
        if sm.rel in allowed:
            continue
        out.extend(scan_tree(sm.tree, sm.rel, forbidden))
    return sorted(out)


def find_violations(root: str, forbidden: str = FORBIDDEN,
                    allowed=None) -> List[Tuple[str, int, str]]:
    try:
        mods, syntax = load_modules(root, ("tpubft",))
    except ScanError:
        # a wrong root (or a package rename) must FAIL, not report a
        # vacuous OK — the enforced-by-construction property would
        # silently stop being enforced
        return [(os.path.join(root, "tpubft"), 0,
                 "no Python modules found to scan — wrong root? "
                 "(expected <root>/tpubft/**/*.py)")]
    return violations_for(mods, syntax, forbidden, allowed)


def run(ctx) -> List[Finding]:
    mods, syntax = ctx.load("tpubft")     # cached parse; loud zero-scan
    findings: List[Finding] = []
    for rel, line, msg in violations_for(mods, syntax):
        findings.append(Finding(PASS_ID, rel, line,
                                f"{rel}:{FORBIDDEN}", msg))
    return findings
