"""Pass: device-seam lint (migrated from tools/check_device_seam.py).

Every kernel call site goes through the breaker-guarded
`device_section(kind)` seam: any reference to the raw `device_dispatch`
gate — import, call, or attribute — outside tpubft/ops/dispatch.py
bypasses failure classification, the OPEN fast-fail, and half-open
probe accounting. tools/check_device_seam.py remains the CLI shim.

ISSUE 16 extends the same confinement to the mesh fan-out plane: a raw
`shard_map` call site outside tpubft/parallel/sharding.py (which owns
the CryptoMesh + every sharded kernel builder) or tpubft/ops/dispatch.py
(the mesh_launch tier) bypasses per-chip breaker eviction and the
launch-failure rebalance loop, so it is rejected by construction too.
"""
from __future__ import annotations

import ast
import os
from typing import List, Optional, Tuple

from tools.tpulint.core import Finding, ScanError, load_modules

PASS_ID = "device-seam"

FORBIDDEN = "device_dispatch"
# the one module allowed to touch the raw gate (it defines it and wraps
# it in the breaker-guarded device_section)
ALLOWED = {os.path.join("tpubft", "ops", "dispatch.py")}
_SEAM_MSG = ("kernel call sites must use the breaker-guarded "
             "device_section(kind) seam (tpubft/ops/dispatch.py)")

MESH_FORBIDDEN = "shard_map"
# the sharding module owns every sharded kernel builder; the dispatch
# module owns the mesh_launch tier that routes to them
MESH_ALLOWED = {os.path.join("tpubft", "parallel", "sharding.py"),
                os.path.join("tpubft", "ops", "dispatch.py")}
_MESH_MSG = ("mesh fan-out must go through tpubft/parallel/sharding.py "
             "(CryptoMesh kernel builders) and the ops/dispatch "
             "mesh_launch tier — a raw shard_map call site bypasses "
             "per-chip breaker eviction and launch-failure rebalance")

# (forbidden name, allowed module set, rationale) — the default rule
# set the pass and the bare CLI apply
RULES: Tuple[Tuple[str, set, str], ...] = (
    (FORBIDDEN, ALLOWED, _SEAM_MSG),
    (MESH_FORBIDDEN, MESH_ALLOWED, _MESH_MSG),
)


def scan_tree(tree: ast.Module, rel: str,
              forbidden: str = FORBIDDEN,
              message: str = _SEAM_MSG) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(tree):
        hit = None
        if isinstance(node, ast.Name) and node.id == forbidden:
            hit = f"references {forbidden}"
        elif isinstance(node, ast.Attribute) and node.attr == forbidden:
            hit = f"references .{forbidden}"
        elif isinstance(node, ast.ImportFrom) \
                and any(a.name == forbidden for a in node.names):
            hit = f"imports {forbidden}"
        if hit:
            out.append((rel, node.lineno, f"{hit} — {message}"))
    return out


def _rules_for(forbidden: Optional[str], allowed) \
        -> Tuple[Tuple[str, set, str], ...]:
    """Explicit (forbidden, allowed) narrows to ONE rule — the legacy
    CLI shim pins the device_dispatch rule this way; the defaults apply
    the full rule set."""
    if forbidden is None:
        return RULES
    for name, allow, msg in RULES:
        if name == forbidden:
            return ((name, allow if allowed is None else allowed, msg),)
    return ((forbidden, allowed or set(), _SEAM_MSG),)


def violations_for(mods, syntax, forbidden: Optional[str] = None,
                   allowed=None) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for f in syntax:
        out.append((f.path, f.line, f.message))
    for name, allow, msg in _rules_for(forbidden, allowed):
        for sm in mods:
            if sm.rel in allow:
                continue
            out.extend(scan_tree(sm.tree, sm.rel, name, msg))
    return sorted(out)


def find_violations(root: str, forbidden: Optional[str] = None,
                    allowed=None) -> List[Tuple[str, int, str]]:
    try:
        mods, syntax = load_modules(root, ("tpubft",))
    except ScanError:
        # a wrong root (or a package rename) must FAIL, not report a
        # vacuous OK — the enforced-by-construction property would
        # silently stop being enforced
        return [(os.path.join(root, "tpubft"), 0,
                 "no Python modules found to scan — wrong root? "
                 "(expected <root>/tpubft/**/*.py)")]
    return violations_for(mods, syntax, forbidden, allowed)


def run(ctx) -> List[Finding]:
    mods, syntax = ctx.load("tpubft")     # cached parse; loud zero-scan
    findings: List[Finding] = []
    for rel, line, msg in violations_for(mods, syntax):
        key = MESH_FORBIDDEN if MESH_FORBIDDEN in msg else FORBIDDEN
        findings.append(Finding(PASS_ID, rel, line, f"{rel}:{key}", msg))
    return findings
