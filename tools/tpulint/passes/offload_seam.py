"""Pass: offload-seam lint (ISSUE 20 — the verified crypto-offload
tier's single-seam guarantee).

The offload tier is safe ONLY because every helper response funnels
through `HelperPool.lease()` and the soundness checks behind the
`*_via_offload` wrappers in `tpubft/offload/pool.py`. A call site that
imports the raw transport (`tpubft.offload.protocol`), talks to the
helper engine directly (`tpubft.offload.helper`), or issues its own
`.lease()` / frame I/O from outside the package gets UNVERIFIED bytes
— a lying helper's output one hop from a consensus verdict. So,
device-seam-style: any lease/transport call site outside

  * `tpubft/offload/`  — the tier itself (pool, soundness, protocol,
                         helper daemon)

is a finding. Consumers integrate via `ops/dispatch.offload_pool()`
and the high-level verified wrappers (`combine_via_offload`,
`sum_via_offload`, `ecdsa_via_offload`) — never the seam internals.
Benchmarks/tests that legitimately drive the raw protocol (fault
injection, the bench harness) live in baseline.toml with their
justification — enumerable, not invisible.
"""
from __future__ import annotations

import ast
import os
from typing import List, Tuple

from tools.tpulint.core import Finding, ScanError, load_modules

PASS_ID = "offload-seam"

# modules whose import OUTSIDE the seam means raw-transport access
FORBIDDEN_MODULES = {
    "tpubft.offload.protocol",
    "tpubft.offload.helper",
}
# attribute calls that issue leases or move raw frames; `lease` with
# keyword/extra args is still a lease — match by name alone
LEASE_ATTRS = {"lease", "send_frame", "recv_frame"}

ALLOWED_PREFIXES = (
    os.path.join("tpubft", "offload") + os.sep,
)
ALLOWED_FILES: set = set()


def scan_tree(tree: ast.Module,
              rel: str) -> List[Tuple[str, int, str, str]]:
    """(rel, line, symbol, message) per violating site; `symbol` keys
    the baseline (stable across line churn, like device-seam)."""
    out: List[Tuple[str, int, str, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in FORBIDDEN_MODULES:
                    out.append((rel, node.lineno, alias.name,
                                f"imports {alias.name} — raw offload "
                                f"transport outside the seam; integrate "
                                f"via ops/dispatch.offload_pool() and "
                                f"the verified *_via_offload wrappers"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in FORBIDDEN_MODULES:
                out.append((rel, node.lineno, mod,
                            f"imports from {mod} — raw offload "
                            f"transport outside the seam; integrate "
                            f"via ops/dispatch.offload_pool() and the "
                            f"verified *_via_offload wrappers"))
            elif mod == "tpubft.offload":
                for alias in node.names:
                    full = f"{mod}.{alias.name}"
                    if full in FORBIDDEN_MODULES:
                        out.append((rel, node.lineno, full,
                                    f"imports {full} — raw offload "
                                    f"transport outside the seam"))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in LEASE_ATTRS:
            out.append((rel, node.lineno, f".{node.func.attr}",
                        f"calls .{node.func.attr}() — lease/frame "
                        f"traffic belongs inside tpubft/offload/; a "
                        f"direct call gets UNVERIFIED helper bytes "
                        f"(no soundness check between a lying helper "
                        f"and a consensus verdict)"))
    return out


def violations_for(mods, syntax) -> List[Tuple[str, int, str, str]]:
    out: List[Tuple[str, int, str, str]] = []
    for f in syntax:
        out.append((f.path, f.line, "syntax", f.message))
    for sm in mods:
        if sm.rel in ALLOWED_FILES \
                or sm.rel.startswith(ALLOWED_PREFIXES):
            continue
        out.extend(scan_tree(sm.tree, sm.rel))
    return sorted(out)


def find_violations(root: str) -> List[Tuple[str, int, str, str]]:
    try:
        mods, syntax = load_modules(root, ("tpubft",))
    except ScanError:
        # a wrong root must FAIL, not report a vacuous OK — same
        # convention as the device-seam lint
        return [(os.path.join(root, "tpubft"), 0, "scan",
                 "no Python modules found to scan — wrong root? "
                 "(expected <root>/tpubft/**/*.py)")]
    return violations_for(mods, syntax)


def run(ctx) -> List[Finding]:
    mods, syntax = ctx.load("tpubft")
    findings: List[Finding] = []
    for rel, line, symbol, msg in violations_for(mods, syntax):
        findings.append(Finding(PASS_ID, rel, line, f"{rel}:{symbol}",
                                msg))
    return findings
