"""Pass: dispatcher hot-path lint (migrated from tools/check_hotpath.py).

The admitted-message handlers — everything an AdmittedMsg reaches
synchronously on the consensus dispatcher — must contain no direct
`unpack()` / `.verify()` / `.verify_batch()` call sites: parse and
signature checks belong to the admission plane (or to the explicitly
named `_verify_*` fallback seams for the admission_workers=0 path).

They must also emit telemetry ONLY through the bounded flight-recorder
API (`flight.record(...)` — tpubft/utils/flight.py): span allocation
(`get_tracer`/`start_span`/`set_tag`) and f-string construction are
per-message heap work the hot path must not pay — the recorder exists
precisely so hot-seam observability costs one tuple into a
preallocated ring. (Logging through %-style lazy formatting stays
allowed: it only formats when the level is live.)

A handler disappearing from the source is itself a violation — the
list must track the code. tools/check_hotpath.py remains the CLI shim.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

from tools.tpulint.core import Finding

PASS_ID = "hotpath"

# (module path, class name) -> function names forming the dispatcher's
# admitted-message hot path: the loop itself plus every handler an
# AdmittedMsg can reach synchronously on the dispatcher thread.
HOT_PATH: Dict[Tuple[str, str], Set[str]] = {
    ("tpubft/consensus/incoming.py", "Dispatcher"): {
        "_loop_body",
    },
    ("tpubft/consensus/replica.py", "Replica"): {
        "_on_admitted",
        "_dispatch_external",
        "_on_client_request",
        "_handle_client_request",
        "_post_admission",
        "_on_pre_prepare",
        "_on_share",
        "_handle_full_cert",
        "_on_checkpoint",
        "_on_time_opinion",
        "_on_ask_to_leave_view",
        "_on_view_change",
        "_on_new_view",
        "_on_restart_ready",
    },
}

FORBIDDEN_CALLS = {"unpack", "verify", "verify_batch"}

# span-allocation observability: per-message heap work the flight
# recorder replaces on the hot path (flight.record is the ONE allowed
# telemetry call in the handlers above)
FORBIDDEN_TELEMETRY = {"get_tracer", "start_span", "set_tag"}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _functions(tree: ast.Module, class_name: str):
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield item


def find_violations(root: str, hot_path=None, forbidden=None,
                    telemetry=None) -> List[Tuple[str, int, str]]:
    hot_path = HOT_PATH if hot_path is None else hot_path
    forbidden = FORBIDDEN_CALLS if forbidden is None else forbidden
    telemetry = FORBIDDEN_TELEMETRY if telemetry is None else telemetry
    out: List[Tuple[str, int, str]] = []
    for (rel, cls), fn_names in sorted(hot_path.items()):
        path = os.path.join(root, rel)
        with open(path, "rb") as f:
            tree = ast.parse(f.read(), filename=path)
        found: Set[str] = set()
        for fn in _functions(tree, cls):
            if fn.name not in fn_names:
                continue
            found.add(fn.name)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and _call_name(node) in forbidden:
                    out.append((
                        os.path.join(rel),
                        node.lineno,
                        f"{cls}.{fn.name} calls {_call_name(node)}() — "
                        f"hot-path handlers must consult the admission "
                        f"verdict / route through a _verify_* seam"))
                elif isinstance(node, ast.Call) \
                        and _call_name(node) in telemetry:
                    out.append((
                        os.path.join(rel),
                        node.lineno,
                        f"{cls}.{fn.name} calls {_call_name(node)}() — "
                        f"hot-path handlers may only emit telemetry "
                        f"through the bounded flight.record() API"))
                elif isinstance(node, ast.JoinedStr):
                    out.append((
                        os.path.join(rel),
                        node.lineno,
                        f"{cls}.{fn.name} builds an f-string — "
                        f"per-message string formatting is forbidden on "
                        f"the hot path; emit flight.record() events or "
                        f"%-style lazy log formatting"))
        for missing in sorted(fn_names - found):
            # a renamed handler silently leaving the lint's coverage is
            # itself a violation: the list must track the code
            out.append((rel, 0,
                        f"{cls}.{missing} not found — update "
                        f"tools/check_hotpath.py HOT_PATH"))
    return sorted(out)


def hot_path_size(hot_path=None) -> int:
    hot_path = HOT_PATH if hot_path is None else hot_path
    return sum(len(v) for v in hot_path.values())


def run(ctx) -> List[Finding]:
    findings: List[Finding] = []
    for rel, line, msg in find_violations(ctx.root):
        findings.append(Finding(PASS_ID, rel, line, f"{rel}:{msg[:60]}",
                                msg))
    return findings
