"""Pass: static lock-order graph.

Collects every `with <lock>:` region (class lock attributes and
module-level lock globals) and records an order edge A→B whenever B is
acquired while A is held — lexically nested regions, plus call edges:
a call made inside A's region to a function that (transitively)
acquires B also records A→B. A cycle in the global graph is a
potential deadlock. This is the static complement of the runtime
`racecheck.LockOrderChecker`, which only sees interleavings that
actually execute under TPUBFT_THREADCHECK.

Lock identity is `ClassName.attr` (Conditions constructed over another
lock attribute unify with it) or `module:<rel>.var` for module
globals; instances of the same class share a node — the usual
conservative choice (per-instance cycles on one class, e.g. a
hand-over-hand pattern, would need instance-sensitive analysis and are
baselined instead).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.tpulint.core import Finding
from tools.tpulint.program import (ClassInfo, FuncInfo, ModuleInfo,
                                   Program, fid_key)
from tools.tpulint.passes.races import _with_locks

PASS_ID = "lock-order"

_MAX_CALL_DEPTH = 4


def _acquires(prog: Program, fi: FuncInfo, memo: Dict, stack: Set,
              depth: int) -> Set[str]:
    """Every lock id this function (or a callee, transitively) can
    acquire. Recursion through the call graph is memoized and
    cycle-cut; depth-limited as a backstop."""
    cached = memo.get(fi.id)
    if cached is not None:
        return cached
    if fi.id in stack or depth > _MAX_CALL_DEPTH:
        return set()
    stack.add(fi.id)
    mi = prog.modules[fi.module]
    ci = mi.classes.get(fi.cls) if fi.cls else None
    out: Set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.With):
            for li in _with_locks(prog, mi, ci, node):
                out.add(li.lock_id)
    for callee, _ in prog.callees(fi):
        out |= _acquires(prog, callee, memo, stack, depth + 1)
    stack.discard(fi.id)
    memo[fi.id] = out
    return out


def _edges_in(prog: Program, mi: ModuleInfo, ci: Optional[ClassInfo],
              fi: FuncInfo, node: ast.AST, held: List[str],
              edges: Dict[Tuple[str, str], Tuple[str, int]],
              acq_memo: Dict) -> None:
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(child, ast.With):
            locks = [li.lock_id for li in _with_locks(prog, mi, ci, child)]
            for lid in locks:
                if held and held[-1] != lid:
                    edges.setdefault((held[-1], lid),
                                     (fi.module, child.lineno))
                held.append(lid)
            _edges_in(prog, mi, ci, fi, child, held, edges, acq_memo)
            del held[len(held) - len(locks):]
            continue
        if isinstance(child, ast.Call) and held:
            local_types = prog._local_types(fi)
            for callee, line in ((c, child.lineno) for c in
                                 prog.resolve_func_ref(fi, child.func,
                                                       local_types)):
                for lid in sorted(_acquires(prog, callee, acq_memo,
                                            set(), 0)):
                    if lid != held[-1]:
                        edges.setdefault((held[-1], lid),
                                         (fi.module, line))
        _edges_in(prog, mi, ci, fi, child, held, edges, acq_memo)


def _sccs(nodes: List[str],
          adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan strongly-connected components."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]
    for root in nodes:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            v, pi = work.pop()
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on.add(v)
            recurse = False
            succs = sorted(adj.get(v, ()))
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work.append((v, i + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return out


def run(ctx) -> List[Finding]:
    prog: Program = ctx.program
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    acq_memo: Dict = {}
    for fid in sorted(prog.funcs, key=fid_key):
        fi = prog.funcs[fid]
        mi = prog.modules[fi.module]
        ci = mi.classes.get(fi.cls) if fi.cls else None
        _edges_in(prog, mi, ci, fi, fi.node, [], edges, acq_memo)

    adj: Dict[str, Set[str]] = {}
    nodes: Set[str] = set()
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        nodes.add(a)
        nodes.add(b)

    findings: List[Finding] = []
    for comp in _sccs(sorted(nodes), adj):
        cyclic = len(comp) > 1 or (comp and comp[0] in
                                   adj.get(comp[0], ()))
        if not cyclic:
            continue
        comp_set = set(comp)
        cyc_edges = sorted((a, b, site) for (a, b), site in edges.items()
                           if a in comp_set and b in comp_set)
        rel, line = cyc_edges[0][2]
        detail = "; ".join(f"{a}→{b} at {s[0]}:{s[1]}"
                           for a, b, s in cyc_edges)
        findings.append(Finding(
            PASS_ID, rel, line,
            "cycle:" + "|".join(sorted(comp_set)),
            f"lock-order cycle over {{{', '.join(sorted(comp_set))}}} — "
            f"two threads taking these locks in opposite orders can "
            f"deadlock; order edges: {detail}"))
    return findings
