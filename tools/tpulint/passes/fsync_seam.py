"""Pass: fsync-seam lint (ISSUE 15 — the durability pipeline's
single-seam guarantee).

Group-commit durability only works if the io thread is the ONE place
that forces ledger bytes to disk: a stray `os.fsync`, a raw
`kvlog_sync`, or a call to the `IDBClient.sync()` group boundary from
anywhere else silently reintroduces the per-run disk tax the pipeline
exists to amortize — and, worse, can land writes out of group order.
So, device-seam-style: any fsync/sync-apply call site outside

  * `tpubft/durability/`            — the pipeline (the seam itself),
  * `tpubft/storage/native.py`      — the engine implementing it (and
                                      the consensus-metadata
                                      `sync_families` carve-out),
  * `tpubft/consensus/persistent.py`— the metadata WAL carve-out
                                      (FilePersistentStorage), which
                                      stays synchronous by design

is a finding. Deliberate exceptions (offline snapshot writers, the
secrets file, the counter app's legacy inline path) live in
baseline.toml with their justification — enumerable, not invisible.
"""
from __future__ import annotations

import ast
import os
from typing import List, Tuple

from tools.tpulint.core import Finding, ScanError, load_modules

PASS_ID = "fsync-seam"

# fully-dotted callables that force bytes to disk
FORBIDDEN_DOTTED = {"os.fsync", "os.fdatasync"}
# attribute names that reach the engine's sync directly or through the
# group boundary: `<db>.sync()` (zero-arg — `sync` with args is some
# other protocol) and the raw ctypes symbol
SYNC_ATTR = "sync"
RAW_SYMBOL = "kvlog_sync"

ALLOWED_PREFIXES = (
    os.path.join("tpubft", "durability") + os.sep,
)
ALLOWED_FILES = {
    os.path.join("tpubft", "storage", "native.py"),
    os.path.join("tpubft", "consensus", "persistent.py"),
    # the abstract seam definition (docstrings + the default no-op)
    os.path.join("tpubft", "storage", "interfaces.py"),
}


def scan_tree(tree: ast.Module,
              rel: str) -> List[Tuple[str, int, str, str]]:
    """(rel, line, symbol, message) per violating call site; `symbol`
    keys the baseline (stable across line churn, like device-seam)."""
    out: List[Tuple[str, int, str, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        dotted = (f"{fn.value.id}.{fn.attr}"
                  if isinstance(fn.value, ast.Name) else None)
        if dotted in FORBIDDEN_DOTTED:
            out.append((rel, node.lineno, dotted,
                        f"calls {dotted}() — synchronous disk flush "
                        f"outside the durability seam; route it through "
                        f"the pipeline (tpubft/durability/) or baseline "
                        f"it with a justification"))
        elif fn.attr == RAW_SYMBOL:
            out.append((rel, node.lineno, RAW_SYMBOL,
                        f"calls .{RAW_SYMBOL}() — raw engine sync "
                        f"bypasses the group-commit seam "
                        f"(NativeDB.sync is the one wrapper)"))
        elif fn.attr == SYNC_ATTR and not node.args and not node.keywords:
            out.append((rel, node.lineno, ".sync",
                        "calls .sync() — the group-commit fsync "
                        "boundary belongs to the durability io thread "
                        "(tpubft/durability/pipeline.py); a per-write "
                        "sync silently reintroduces the per-run disk "
                        "tax"))
    return out


def violations_for(mods, syntax) -> List[Tuple[str, int, str, str]]:
    out: List[Tuple[str, int, str, str]] = []
    for f in syntax:
        out.append((f.path, f.line, "syntax", f.message))
    for sm in mods:
        if sm.rel in ALLOWED_FILES \
                or sm.rel.startswith(ALLOWED_PREFIXES):
            continue
        out.extend(scan_tree(sm.tree, sm.rel))
    return sorted(out)


def find_violations(root: str) -> List[Tuple[str, int, str, str]]:
    try:
        mods, syntax = load_modules(root, ("tpubft",))
    except ScanError:
        # a wrong root must FAIL, not report a vacuous OK — same
        # convention as the device-seam lint
        return [(os.path.join(root, "tpubft"), 0, "scan",
                 "no Python modules found to scan — wrong root? "
                 "(expected <root>/tpubft/**/*.py)")]
    return violations_for(mods, syntax)


def run(ctx) -> List[Finding]:
    mods, syntax = ctx.load("tpubft")
    findings: List[Finding] = []
    for rel, line, symbol, msg in violations_for(mods, syntax):
        findings.append(Finding(PASS_ID, rel, line, f"{rel}:{symbol}",
                                msg))
    return findings
