"""Pass: static race detection.

Any `self.<attr>` store inside a function reachable from two or more
thread roles must be lexically enclosed in a `with self.<lock>:` region
whose lock attribute was constructed by `racecheck.make_lock` /
`make_condition` (lock attribution is by AST region — the static
counterpart of the lock-discipline property TSan approximates with
happens-before at runtime). Three finding shapes:

  * unguarded     — no lock region encloses the store at all;
  * raw-lock      — a region encloses it, but the lock is a bare
    `threading.Lock/RLock/Condition`, invisible to the runtime
    lock-order graph (`TPUBFT_THREADCHECK`): migrate it to
    `make_lock`/`make_condition`;
  * foreign-store — an unguarded store THROUGH A PARAMETER annotated
    with a repo class (`def _run(self, collector: ShareCollector): ...
    collector.attr = v`) where the WRITERS of that class attribute —
    its own methods' self-stores plus every annotated-parameter store,
    across the whole program — span two or more thread roles. The
    self-store check cannot see these (the store isn't on `self`, and
    each writing function may be single-role), but two single-role
    writers on different threads are exactly the CollectorPool._run
    seam: the sig-combine worker flipped `collector.job_launched` while
    the dispatcher (the attribute's other writer) owned it. Stores into
    another role's object must route through the owning role (post a
    message back) or take a registered lock.

Deliberate under-approximations (documented in docs/OPERATIONS.md):
stores in `__init__`/`__post_init__` precede thread start
(happens-before via Thread.start); `start`/`stop` are lifecycle
transitions — the threads they race against are the ones they create
(Thread.start) or join (Thread.join), both happens-before edges;
methods named `*_locked` follow the repo convention that the caller
holds the class lock; only stores are checked (a single-writer
attribute read cross-thread is the Python memory model's torn-free
case).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.tpulint.core import Finding
from tools.tpulint.program import (ClassInfo, FuncInfo, LockInfo,
                                   ModuleInfo, Program, fid_key)

PASS_ID = "static-race"

EXEMPT_METHODS = {"__init__", "__new__", "__post_init__",
                  "__init_subclass__", "start", "stop"}


def _roles_label(roles: Sequence[str]) -> str:
    rs = sorted(roles)
    label = "×".join(rs[:2])
    if len(rs) > 2:
        label += f"(+{len(rs) - 2})"
    return label


def _with_locks(prog: Program, mi: ModuleInfo, ci: Optional[ClassInfo],
                node: ast.With) -> List[LockInfo]:
    out: List[LockInfo] = []
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
                and e.value.id == "self" and ci is not None:
            li = prog.class_lock(ci, e.attr)
            if li is not None:
                out.append(li)
        elif isinstance(e, ast.Name) and e.id in mi.locks:
            out.append(mi.locks[e.id])
    return out


def _store_targets(node: ast.AST) -> List[ast.Attribute]:
    """`self.<attr>` targets of an assignment statement."""
    targets: List[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if isinstance(node, ast.AnnAssign) and node.value is None:
            return []
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = list(node.targets)
    out: List[ast.Attribute] = []
    stack = targets
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            out.append(t)
    return out


def _scan(prog: Program, mi: ModuleInfo, ci: ClassInfo, fi: FuncInfo,
          roles: Sequence[str], node: ast.AST, held: List[LockInfo],
          findings: List[Finding]) -> None:
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue                       # its own FuncInfo / not now
        if isinstance(child, ast.With):
            locks = _with_locks(prog, mi, ci, child)
            held.extend(locks)
            _scan(prog, mi, ci, fi, roles, child, held, findings)
            del held[len(held) - len(locks):]
            continue
        for t in _store_targets(child):
            attr = t.attr
            if prog.class_lock(ci, attr) is not None:
                continue                   # the lock attribute itself
            if not held:
                findings.append(Finding(
                    PASS_ID, fi.module, child.lineno,
                    f"{fi.module}:{fi.qualname}:{attr}",
                    f"{_roles_label(roles)} self.{attr} — unguarded "
                    f"cross-thread store in {fi.qualname} (reachable "
                    f"from roles {sorted(roles)}); wrap it in a "
                    f"`with self.<lock>:` region built by "
                    f"racecheck.make_lock"))
            elif not any(li.registered for li in held):
                li = held[-1]
                findings.append(Finding(
                    PASS_ID, fi.module, child.lineno,
                    f"{fi.module}:{fi.qualname}:{attr}:raw-lock",
                    f"{_roles_label(roles)} self.{attr} — store in "
                    f"{fi.qualname} guarded only by raw lock "
                    f"{li.lock_id} ({li.kind}); construct it with "
                    f"racecheck.make_lock/make_condition so the "
                    f"runtime lock-order graph sees it"))
        _scan(prog, mi, ci, fi, roles, child, held, findings)


def _attr_writer_roles(prog: Program, roles_map
                       ) -> Dict[Tuple[str, str, str], Set[str]]:
    """(class module, class name, attr) -> union of thread roles that
    STORE the attribute anywhere in the program: the class's own
    methods' self-stores plus stores through class-annotated parameters.
    Lifecycle methods (EXEMPT_METHODS, `*_locked`) don't count — their
    writes happen-before/behind the threading they bracket."""
    from tools.tpulint.program import walk_body
    out: Dict[Tuple[str, str, str], Set[str]] = {}
    for fid, fi in prog.funcs.items():
        roles_f = roles_map.get(fid, set())
        if not roles_f:
            continue
        leaf = fi.name.rsplit(".", 1)[-1]
        if leaf in EXEMPT_METHODS or leaf.endswith("_locked"):
            continue
        mi = prog.modules[fi.module]
        ptypes = prog._param_types(mi, fi)
        for node in walk_body(fi.node):
            for t in _store_targets(node):
                if fi.cls is not None:
                    out.setdefault((fi.module, fi.cls, t.attr),
                                   set()).update(roles_f)
            for t in _param_store_targets(node, ptypes):
                owner = ptypes[t.value.id]
                out.setdefault((owner.module, owner.name, t.attr),
                               set()).update(roles_f)
    return out


def _foreign_scan(prog: Program, mi: ModuleInfo, fi: FuncInfo,
                  roles_f: Set[str], ptypes: Dict[str, ClassInfo],
                  writers, node: ast.AST,
                  held: List[LockInfo], findings: List[Finding]) -> None:
    ci = mi.classes.get(fi.cls) if fi.cls else None
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(child, ast.With):
            locks = _with_locks(prog, mi, ci, child)
            held.extend(locks)
            _foreign_scan(prog, mi, fi, roles_f, ptypes, writers,
                          child, held, findings)
            del held[len(held) - len(locks):]
            continue
        for t in _param_store_targets(child, ptypes):
            base, attr = t.value.id, t.attr
            owner = ptypes[base]
            combined = roles_f | writers.get(
                (owner.module, owner.name, attr), set())
            if len(combined) < 2:
                continue
            if any(li.registered for li in held):
                continue               # guarded by an instrumented lock
            findings.append(Finding(
                PASS_ID, fi.module, child.lineno,
                f"{fi.module}:{fi.qualname}:{base}.{attr}:foreign",
                f"{_roles_label(sorted(combined))} {base}.{attr} — "
                f"foreign store in {fi.qualname}: {owner.name}.{attr} "
                f"has writers on roles {sorted(combined)}; route the "
                f"write through the owning role (post a message back) "
                f"or guard every writer with a racecheck.make_lock "
                f"region"))
        _foreign_scan(prog, mi, fi, roles_f, ptypes, writers,
                      child, held, findings)


def _param_store_targets(node: ast.AST, ptypes: Dict[str, ClassInfo]
                         ) -> List[ast.Attribute]:
    """`<param>.<attr>` targets of an assignment where <param> has a
    repo-class annotation."""
    targets: List[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if isinstance(node, ast.AnnAssign) and node.value is None:
            return []
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = list(node.targets)
    out: List[ast.Attribute] = []
    stack = targets
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id != "self" and t.value.id in ptypes:
            out.append(t)
    return out


def run(ctx) -> List[Finding]:
    prog: Program = ctx.program
    roles_map, _ = ctx.ensure_roles()
    findings: List[Finding] = []
    writers = _attr_writer_roles(prog, roles_map)
    for fid in sorted(roles_map, key=fid_key):
        roles = roles_map[fid]
        if len(roles) < 2:
            continue
        fi = prog.funcs.get(fid)
        if fi is None or fi.cls is None:
            continue
        leaf = fi.name.rsplit(".", 1)[-1]
        if leaf in EXEMPT_METHODS or leaf.endswith("_locked"):
            continue
        mi = prog.modules[fi.module]
        ci = mi.classes.get(fi.cls)
        if ci is None:
            continue
        _scan(prog, mi, ci, fi, sorted(roles), fi.node, [], findings)
    # foreign-store sweep: every function (roled or not) storing
    # through a class-annotated parameter
    for fid in sorted(prog.funcs, key=fid_key):
        fi = prog.funcs[fid]
        leaf = fi.name.rsplit(".", 1)[-1]
        if leaf in EXEMPT_METHODS or leaf.endswith("_locked"):
            continue
        mi = prog.modules[fi.module]
        ptypes = prog._param_types(mi, fi)
        if not ptypes:
            continue
        _foreign_scan(prog, mi, fi, roles_map.get(fid, set()), ptypes,
                      writers, fi.node, [], findings)
    return findings
