"""Pass: thread-role inference.

Seeds every declared thread entry point / cross-thread API surface
(tools/tpulint/rolemap.py) with its role, adds the callback-registrar
rules (dispatcher timers/handlers, health probes), then propagates
roles through the conservative call graph to a fixpoint: a function's
role set is every thread role it can run under. Downstream passes
(static-race, dispatcher-blocking) consume the map via
`ctx.ensure_roles()`.

Findings:
  * stale seed — a rolemap entry naming a function that no longer
    exists (the map must track the code, like check_hotpath.HOT_PATH);
  * unseeded thread entry point — `threading.Thread(target=f)` where
    `f` is a repo function with no THREAD_ROLES entry (an unseeded
    thread is unanalyzed code).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from tools.tpulint import rolemap
from tools.tpulint.core import Finding
from tools.tpulint.program import (FuncId, FuncInfo, Program,
                                   dotted_expr, fid_key, walk_body)

PASS_ID = "thread-roles"


def _seed(prog: Program, table, roles, findings: List[Finding],
          kind: str) -> None:
    for fid, rs in sorted(table.items(), key=lambda kv: fid_key(kv[0])):
        if fid not in prog.funcs:
            rel, cls, name = fid
            qual = f"{cls}.{name}" if cls else name
            findings.append(Finding(
                PASS_ID, rel, 0, f"stale-seed:{rel}:{qual}",
                f"stale {kind} seed: {qual} not found in {rel} — update "
                f"tools/tpulint/rolemap.py"))
            continue
        roles.setdefault(fid, set()).update(rs)


def _callback_args(call: ast.Call, spec) -> List[ast.AST]:
    pos_idx, kw_names, _role = spec
    out: List[ast.AST] = []
    for i in pos_idx:
        if i < len(call.args):
            out.append(call.args[i])
    for kw in call.keywords:
        if kw.arg in kw_names:
            out.append(kw.value)
    return out


def compute_roles(ctx) -> Tuple[Dict[FuncId, Set[str]], List[Finding]]:
    prog: Program = ctx.program
    findings: List[Finding] = []
    roles: Dict[FuncId, Set[str]] = {}

    _seed(prog, rolemap.THREAD_ROLES, roles, findings, "thread")
    _seed(prog, rolemap.API_SEEDS, roles, findings, "API")

    # one structural sweep: registrar callbacks + thread-target audit
    for fi in sorted(prog.funcs.values(),
                     key=lambda f: fid_key(f.id)):
        mi = prog.modules[fi.module]
        # walk_body: a nested closure is its own FuncInfo in this very
        # loop — ast.walk here would visit its calls twice
        for node in walk_body(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fname = None
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            spec = rolemap.REGISTRARS.get(fname or "")
            if spec is not None:
                for arg in _callback_args(node, spec):
                    for cb in prog.resolve_func_ref(fi, arg):
                        roles.setdefault(cb.id, set()).add(spec[2])
                continue
            d = dotted_expr(node.func)
            if d and prog.resolve_dotted(mi, d) == "threading.Thread":
                target = next((kw.value for kw in node.keywords
                               if kw.arg == "target"), None)
                if target is None:
                    continue
                for tf in prog.resolve_func_ref(fi, target):
                    if tf.id not in rolemap.THREAD_ROLES:
                        findings.append(Finding(
                            PASS_ID, fi.module, node.lineno,
                            f"unseeded-thread:{fi.module}:{tf.qualname}",
                            f"unseeded thread entry point "
                            f"{tf.qualname} — declare its role in "
                            f"tools/tpulint/rolemap.py THREAD_ROLES so "
                            f"the analyzer can classify the code it "
                            f"runs"))

    # propagate to fixpoint through the call graph
    work = [fid for fid in roles]
    while work:
        fid = work.pop()
        fi = prog.funcs.get(fid)
        if fi is None:
            continue
        src = roles.get(fid, set())
        if not src:
            continue
        for callee, _line in prog.callees(fi):
            dst = roles.setdefault(callee.id, set())
            missing = src - dst
            if missing:
                dst.update(missing)
                work.append(callee.id)
    return roles, findings


def run(ctx) -> List[Finding]:
    _roles, findings = ctx.ensure_roles()
    return findings
