"""Pass registry. Order matters only for readability of reports;
role inference is computed on demand (ctx.ensure_roles) by whichever
dependent pass runs first."""
from __future__ import annotations

from tools.tpulint.passes import (blocking, crashpoints, device_seam,
                                  fsync_seam, hotpath, imports_,
                                  lockorder, offload_seam, races, roles)

# pass id -> module exposing run(ctx) -> List[Finding]
REGISTRY = {
    roles.PASS_ID: roles,                 # thread-roles
    races.PASS_ID: races,                 # static-race
    lockorder.PASS_ID: lockorder,         # lock-order
    blocking.PASS_ID: blocking,           # dispatcher-blocking
    imports_.PASS_ID: imports_,           # imports
    hotpath.PASS_ID: hotpath,             # hotpath
    device_seam.PASS_ID: device_seam,     # device-seam
    fsync_seam.PASS_ID: fsync_seam,       # fsync-seam (durability)
    offload_seam.PASS_ID: offload_seam,   # offload-seam (crypto tier)
    crashpoints.PASS_ID: crashpoints,     # crashpoints
}
