"""Pass: dispatcher blocking-call lint.

The consensus dispatcher is THE protocol thread — every handler runs
on it, so anything that parks it (a sleep, a thread join, a blocking
socket/subprocess call, an fsync it didn't budget for, a device
compile) stalls ordering for the whole replica. Any function whose
inferred role set includes `dispatcher` must not call into the
blocking table below. Legitimately-blocking dispatcher seams — the
deliberate durability fsyncs, the bounded view-change drain barrier —
are baselined with their justification rather than exempted in code,
so every blocking site on the control thread is enumerable.

`.join()` is flagged only with zero positional arguments: a thread
join is `t.join()` / `t.join(timeout=...)`, while `str.join` always
takes exactly one positional iterable.
"""
from __future__ import annotations

import ast
from typing import List

from tools.tpulint.core import Finding
from tools.tpulint.program import (Program, dotted_expr, fid_key,
                                   walk_body)

PASS_ID = "dispatcher-blocking"

# fully-qualified callables that park the calling thread
BLOCKING_DOTTED = {
    "time.sleep": "sleeps the consensus thread",
    "os.fsync": "synchronous disk flush",
    "os.fdatasync": "synchronous disk flush",
    "select.select": "blocking fd wait",
    "socket.create_connection": "blocking connect",
    "subprocess.run": "blocking subprocess",
    "subprocess.call": "blocking subprocess",
    "subprocess.check_call": "blocking subprocess",
    "subprocess.check_output": "blocking subprocess",
    # first-touch device compile: tracing + XLA compilation ride the
    # caller; warm kernels belong to bring-up, never to the dispatcher
    "jax.jit": "device compile on first call",
    "jax.device_put": "host→device transfer",
}

# method names that block regardless of receiver type
BLOCKING_METHODS = {
    "fsync": "synchronous disk flush",
    "fdatasync": "synchronous disk flush",
    "serve_forever": "blocks forever",
    "recvfrom": "blocking socket receive",
    "accept": "blocking socket accept",
}


def run(ctx) -> List[Finding]:
    prog: Program = ctx.program
    roles_map, _ = ctx.ensure_roles()
    findings: List[Finding] = []
    for fid in sorted(roles_map, key=fid_key):
        if "dispatcher" not in roles_map[fid]:
            continue
        fi = prog.funcs.get(fid)
        if fi is None:
            continue
        mi = prog.modules[fi.module]
        for node in walk_body(fi.node):
            if not isinstance(node, ast.Call):
                continue
            label = None
            name = None
            d = dotted_expr(node.func)
            if d:
                full = prog.resolve_dotted(mi, d)
                if full in BLOCKING_DOTTED:
                    name, label = full, BLOCKING_DOTTED[full]
            if label is None and isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in BLOCKING_METHODS:
                    name, label = f".{attr}", BLOCKING_METHODS[attr]
                elif attr == "join" and not node.args:
                    name, label = ".join", "thread join"
            if label is None:
                continue
            findings.append(Finding(
                PASS_ID, fi.module, node.lineno,
                f"{fi.module}:{fi.qualname}:{name}",
                f"{fi.qualname} runs on the dispatcher but calls "
                f"{name}() — {label}; move it off the control thread "
                f"(admission/exec lane/background) or baseline it with "
                f"the justification"))
    return findings
