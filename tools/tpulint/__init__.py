"""tpulint — whole-program concurrency analyzer + unified lint runner.

One runner (`python -m tools.tpulint`, wired into tier-1 by
tests/test_tpulint.py) over eight passes:

  thread-roles        seed + propagate which thread(s) every function
                      can run on (tools/tpulint/rolemap.py)
  static-race         cross-role `self.<attr>` stores must sit in a
                      make_lock/make_condition region (AST attribution)
  lock-order          global static lock-order graph; cycles fail
                      (complements the runtime LockOrderChecker, which
                      only sees executed paths)
  dispatcher-blocking no sleep/join/socket/fsync/device-compile on the
                      consensus thread
  imports / hotpath / device-seam / crashpoints
                      the four historical tools/check_*.py lints,
                      re-hosted on the shared loader (their CLI shims
                      remain for back-compat)

Findings are suppressed only through tools/tpulint/baseline.toml —
every entry carries a one-line justification, stale or malformed
entries fail the run (see docs/OPERATIONS.md "Static analysis &
concurrency lint").
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.tpulint import rolemap
from tools.tpulint.core import (BaselineError, Finding, ScanError,
                                apply_baseline, load_modules,
                                parse_baseline)
from tools.tpulint.program import Program

DEFAULT_BASELINE = os.path.join("tools", "tpulint", "baseline.toml")


class Context:
    """Shared per-run state: one module load and one Program build,
    reused by every pass."""

    def __init__(self, root: str) -> None:
        self.root = root
        self._loads: Dict[Tuple[str, ...], tuple] = {}
        self._program: Optional[Program] = None
        self._roles: Optional[tuple] = None

    def load(self, *subdirs: str):
        """(modules, syntax-error findings) for the given scan roots —
        cached; raises ScanError on a zero-module scan."""
        key = tuple(subdirs)
        if key not in self._loads:
            self._loads[key] = load_modules(self.root, subdirs)
        return self._loads[key]

    @property
    def program(self) -> Program:
        """Whole-program index over tpubft/ minus the test-harness
        exclusions (rolemap.CONCURRENCY_EXCLUDE)."""
        if self._program is None:
            mods, _ = self.load("tpubft")
            keep = [m for m in mods
                    if not m.rel.replace(os.sep, "/").startswith(
                        rolemap.CONCURRENCY_EXCLUDE)]
            self._program = Program(
                keep, attr_hints=rolemap.ATTR_TYPE_HINTS,
                return_hints=rolemap.RETURN_TYPE_HINTS)
        return self._program

    def ensure_roles(self):
        if self._roles is None:
            from tools.tpulint.passes.roles import compute_roles
            self._roles = compute_roles(self)
        return self._roles


def run_passes(root: str, pass_ids: Optional[Sequence[str]] = None,
               ) -> List[Finding]:
    """Run the requested passes (default: all) and return raw findings
    (pre-baseline). Loader syntax errors surface once."""
    from tools.tpulint.passes import REGISTRY
    ids = list(pass_ids) if pass_ids else list(REGISTRY)
    unknown = [p for p in ids if p not in REGISTRY]
    if unknown:
        raise ScanError(f"unknown pass(es): {', '.join(unknown)} "
                        f"(known: {', '.join(REGISTRY)})")
    ctx = Context(root)
    findings: List[Finding] = []
    seen_syntax: Set[str] = set()
    for pid in ids:
        for f in REGISTRY[pid].run(ctx):
            if f.pass_id == "loader":
                if f.key in seen_syntax:
                    continue
                seen_syntax.add(f.key)
            findings.append(f)
    return findings


def analyze(root: str, pass_ids: Optional[Sequence[str]] = None,
            baseline_path: Optional[str] = None
            ) -> Tuple[List[Finding], int, List[Finding]]:
    """(surviving findings, n_suppressed, baseline errors)."""
    from tools.tpulint.passes import REGISTRY
    findings = run_passes(root, pass_ids)
    if baseline_path is None:
        return findings, 0, []
    rel = os.path.relpath(baseline_path, root)
    entries = parse_baseline(baseline_path) \
        if os.path.exists(baseline_path) else []
    known = list(REGISTRY) + ["loader"]
    if pass_ids:
        # partial run: entries for passes that did not run are neither
        # applied nor stale-checked (their findings were never
        # computed) — but unknown-pass entries must still fail
        selected = set(pass_ids) | {"loader"}
        entries = [e for e in entries
                   if e.pass_id in selected or e.pass_id not in known]
    return apply_baseline(findings, entries, known, rel)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tpulint",
        description="whole-program concurrency analyzer / lint runner")
    ap.add_argument("root", nargs="?", default=None,
                    help="repo root (default: the tree containing this "
                         "package)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: "
                         "tools/tpulint/baseline.toml under root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report raw findings, apply no suppressions")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    from tools.tpulint.passes import REGISTRY
    if args.list_passes:
        for pid, mod in REGISTRY.items():
            first = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{pid:20s} {first}")
        return 0

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pass_ids = args.passes.split(",") if args.passes else None
    baseline = None if args.no_baseline else (
        args.baseline or os.path.join(root, DEFAULT_BASELINE))
    try:
        findings, n_suppressed, errors = analyze(root, pass_ids, baseline)
    except (ScanError, BaselineError) as e:
        print(f"tpulint: FATAL: {e}", file=sys.stderr)
        return 2
    for f in findings + errors:
        print(f.render())
    if findings or errors:
        print(f"tpulint: {len(findings)} finding(s), "
              f"{len(errors)} baseline error(s), "
              f"{n_suppressed} suppressed", file=sys.stderr)
        return 1
    n = len(pass_ids) if pass_ids else len(REGISTRY)
    print(f"OK: tpulint clean — {n} pass(es), "
          f"{n_suppressed} baselined finding(s)")
    return 0
