#!/bin/bash
# TPU capture daemon — polls for a compute-capable device window and runs
# the docs/TPU_CAPTURE.md sequence the moment one opens. All output under
# /tmp/capture/. Each step leaves a .done marker; steps that fail (the
# window closing mid-capture) are retried in later windows. Exits 0 only
# when EVERY step has succeeded, 1 if the deadline passes first.
#
# Probe = real compute in a bounded subprocess (device init hangs forever
# when the tunnel is down, and listing devices can succeed while compute
# hangs — only a completed matmul counts).
set -u
OUT=/tmp/capture
mkdir -p "$OUT"
DEADLINE=$(( $(date +%s) + ${CAPTURE_WINDOW_S:-39600} ))   # default 11h
PROBE_TIMEOUT=${PROBE_TIMEOUT_S:-150}
cd /root/repo

log() { echo "$(date -u +%FT%TZ) $*" >> "$OUT/daemon.log"; }

probe() {
  timeout "$PROBE_TIMEOUT" python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != "cpu"
x = jnp.ones((8, 128))
assert float((x @ x.T).sum()) == 8 * 128 * 8
EOF
}

bench_step() {
  # bounded Mosaic bring-up first: a WEDGED compile of the fused kernel
  # must cost one 900s probe, not the whole window — on failure/hang the
  # bench still captures the XLA kernel number
  local skip_pallas=""
  if ! timeout 900 python -m tools.pallas_bringup --rung 5 \
      > "$OUT/bringup.log" 2>&1; then
    log "bringup rung5 failed/hung (rc=$?): bench will skip pallas"
    skip_pallas=1
  fi
  TPUBFT_SKIP_PALLAS=$skip_pallas TPUBFT_BENCH_DEVICE_WAIT_S=0 \
    timeout 1800 python bench.py \
    > "$OUT/bench.json" 2> "$OUT/bench.err"
  local rc=$?
  log "bench rc=$rc $(tail -c 300 "$OUT/bench.json")"
  # a degraded (CPU-fallback) record means the window closed: not a capture
  [ "$rc" = 0 ] || return 1
  grep -q '"degraded"' "$OUT/bench.json" && return 1
  # archive the hardware record into the repo so a later tunnel-down
  # driver run can still surface it (bench.py attaches it as
  # "last_hw_capture" on degraded fallbacks)
  mkdir -p /root/repo/benchmarks/captures
  python - "$OUT/bench.json" <<'EOF'
import json, subprocess, sys, time
rec = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
commit = subprocess.run(["git", "-C", "/root/repo", "rev-parse", "--short", "HEAD"],
                        capture_output=True, text=True).stdout.strip()
out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
       "commit": commit, "record": rec}
open("/root/repo/benchmarks/captures/latest_tpu.json", "w").write(
    json.dumps(out, indent=1) + "\n")
EOF
}

e2e_run() {  # $1 log name, $2 timeout, $3... extra flags
  local logname=$1 tmo=$2; shift 2
  timeout "$tmo" python -m benchmarks.bench_e2e --configs 1,2 --backends tpu \
    --secs 10 "$@" > "$OUT/$logname.log" 2>&1 \
    && grep -q '"ops_per_sec"' "$OUT/$logname.log"
}

e2e_inproc_step() { e2e_run e2e_inproc 900; }

e2e_proc_step() { e2e_run e2e_proc 1200 --processes; }

crossover_step() {
  timeout 1800 python -m benchmarks.bench_msm_crossover --ks 8,32,128,512,667 \
    > "$OUT/msm_crossover.log" 2>&1
}

flood_step() {
  timeout 1800 python -m benchmarks.bench_flood --n 1000 --reps 3 \
    > "$OUT/flood.log" 2>&1
}

STEPS="bench e2e_inproc e2e_proc crossover flood"

run_step() {  # $1 = step name; skips if already .done, marks on success
  local name=$1
  [ -e "$OUT/$name.done" ] && return 0
  "${name}_step"
  local rc=$?
  log "step $name rc=$rc"
  if [ "$rc" = 0 ]; then
    touch "$OUT/$name.done"
    return 0
  fi
  return 1
}

all_done() {
  for s in $STEPS; do [ -e "$OUT/$s.done" ] || return 1; done
}

done_count() {
  local n=0
  for s in $STEPS; do [ -e "$OUT/$s.done" ] && n=$((n + 1)); done
  echo "$n"
}

set -- $STEPS
TOTAL=$#

# a fresh daemon is a fresh capture intent: stale markers from an earlier
# run (possibly at an older commit) must not short-circuit this one.
# CAPTURE_KEEP_MARKERS=1 resumes a partial capture instead.
if [ "${CAPTURE_KEEP_MARKERS:-0}" != 1 ]; then
  for s in $STEPS; do rm -f "$OUT/$s.done"; done
fi

log "capture daemon start (deadline in $((DEADLINE-$(date +%s)))s, $(done_count)/$TOTAL steps pre-marked)"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    log "WINDOW OPEN — running pending capture steps"
    for s in $STEPS; do
      run_step "$s" || break   # window likely closed; re-probe first
    done
    if all_done; then
      log "CAPTURE COMPLETE (all steps)"
      exit 0
    fi
    log "capture incomplete ($(done_count)/$TOTAL steps); resuming poll"
  else
    log "no window"
  fi
  sleep "${PROBE_INTERVAL_S:-45}"
done
log "deadline passed; steps done: $(done_count)/$TOTAL"
exit 1
