#!/bin/bash
# TPU capture daemon — polls for a compute-capable device window and runs
# the docs/TPU_CAPTURE.md sequence the moment one opens. All output under
# /tmp/capture/. Exits 0 after a successful capture, 1 if the deadline
# passes with no window.
#
# Probe = real compute in a bounded subprocess (device init hangs forever
# when the tunnel is down, and listing devices can succeed while compute
# hangs — only a completed matmul counts).
set -u
OUT=/tmp/capture
mkdir -p "$OUT"
DEADLINE=$(( $(date +%s) + ${CAPTURE_WINDOW_S:-39600} ))   # default 11h
PROBE_TIMEOUT=${PROBE_TIMEOUT_S:-150}
cd /root/repo

probe() {
  timeout "$PROBE_TIMEOUT" python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != "cpu"
x = jnp.ones((8, 128))
assert float((x @ x.T).sum()) == 8 * 128 * 8
EOF
}

echo "$(date -u +%FT%TZ) capture daemon start (deadline in $((DEADLINE-$(date +%s)))s)" >> "$OUT/daemon.log"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "$(date -u +%FT%TZ) WINDOW OPEN — starting capture" >> "$OUT/daemon.log"
    # 1. north-star bench (device confirmed: skip the retry-wait)
    TPUBFT_BENCH_DEVICE_WAIT_S=0 timeout 1800 python bench.py \
      > "$OUT/bench.json" 2> "$OUT/bench.err"
    rc=$?
    echo "$(date -u +%FT%TZ) bench rc=$rc $(tail -c 300 "$OUT/bench.json")" >> "$OUT/daemon.log"
    if [ "$rc" != 0 ] || grep -q '"degraded"' "$OUT/bench.json"; then
      # the window closed under us (bench fell back to CPU or died):
      # this is NOT a capture — resume polling for a real window
      echo "$(date -u +%FT%TZ) window lost mid-capture; resuming poll" >> "$OUT/daemon.log"
      sleep "${PROBE_INTERVAL_S:-45}"
      continue
    fi
    # archive the hardware record into the repo so a later tunnel-down
    # driver run can still surface it (bench.py attaches it as
    # "last_hw_capture" on degraded fallbacks)
    mkdir -p /root/repo/benchmarks/captures
    python - "$OUT/bench.json" <<'EOF'
import json, subprocess, sys, time
rec = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
commit = subprocess.run(["git", "-C", "/root/repo", "rev-parse", "--short", "HEAD"],
                        capture_output=True, text=True).stdout.strip()
out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
       "commit": commit, "record": rec}
open("/root/repo/benchmarks/captures/latest_tpu.json", "w").write(
    json.dumps(out, indent=1) + "\n")
EOF
    # 2. e2e with the tpu backend
    timeout 900 python -m benchmarks.bench_e2e --configs 1,2 --backends tpu --secs 10 \
      > "$OUT/e2e_inproc.log" 2>&1
    echo "$(date -u +%FT%TZ) e2e-inproc rc=$?" >> "$OUT/daemon.log"
    timeout 1200 python -m benchmarks.bench_e2e --configs 1,2 --backends tpu --secs 10 --processes \
      > "$OUT/e2e_proc.log" 2>&1
    echo "$(date -u +%FT%TZ) e2e-proc rc=$?" >> "$OUT/daemon.log"
    # 3. MSM combine crossover
    timeout 1800 python -m benchmarks.bench_msm_crossover --ks 8,32,128,512,667 \
      > "$OUT/msm_crossover.log" 2>&1
    echo "$(date -u +%FT%TZ) crossover rc=$?" >> "$OUT/daemon.log"
    # 4. config-4 flood
    timeout 1800 python -m benchmarks.bench_flood --n 1000 --reps 3 \
      > "$OUT/flood.log" 2>&1
    echo "$(date -u +%FT%TZ) flood rc=$?" >> "$OUT/daemon.log"
    echo "$(date -u +%FT%TZ) CAPTURE COMPLETE" >> "$OUT/daemon.log"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) no window" >> "$OUT/daemon.log"
  sleep "${PROBE_INTERVAL_S:-45}"
done
echo "$(date -u +%FT%TZ) deadline passed, no window" >> "$OUT/daemon.log"
exit 1
