"""Import-hygiene lint: no module-level third-party imports in tpubft/.

CLI/back-compat shim — the implementation now lives in the unified
analyzer framework (tools/tpulint/passes/imports_.py; run everything
with `python -m tools.tpulint`). The rule: a module-level `import X` /
`from X import ...` may only name the stdlib, the repo's own packages,
or an approved always-present dependency (`jax`, `numpy`); optional
packages import inside functions or behind a `try:` soft-import guard
(the seed regression: a module-level `import cryptography` broke
collection of 32/51 test modules).

Usage:
  python tools/check_imports.py [root]     # default: tpubft/
Exit 1 with one line per violation. Wired into tier-1 by
tests/test_check_imports.py.
"""
from __future__ import annotations

import os
import sys
from typing import List

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.tpulint.passes import imports_ as _impl  # noqa: E402

APPROVED = set(_impl.APPROVED)
INTERNAL = set(_impl.INTERNAL)


def find_violations(root: str):
    return _impl.find_violations(root, approved=APPROVED,
                                 internal=INTERNAL)


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(_ROOT, "tpubft")
    violations = find_violations(root)
    for path, lineno, mod in violations:
        print(f"{path}:{lineno}: module-level import of third-party "
              f"package {mod!r} (use a function-level or try-guarded "
              f"import; approved always-on deps: {sorted(APPROVED)})")
    if violations:
        return 1
    print(f"OK: no module-level third-party imports under {root}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
