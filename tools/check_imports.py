"""Import-hygiene lint: no module-level third-party imports in tpubft/.

The product tree must import cleanly in a bare environment — the whole
point of the self-hosted crypto engine (tpubft/crypto/scalar.py) is that
nothing under tpubft/ hard-depends on an uninstallable package (the seed
regression: a module-level `import cryptography` in crypto/cpu.py broke
collection of 32/51 test modules on hosts without OpenSSL bindings).

Rule: a module-level `import X` / `from X import ...` (executed at
import time — anything outside a function/class body and outside a
`try:` soft-import guard) may only name the stdlib, the repo's own
packages, or an approved always-present dependency (`jax`, `numpy` —
baked into the image). Optional packages must be imported inside
functions or behind a runtime feature probe (crypto/cpu._openssl()).

Usage:
  python tools/check_imports.py [root]     # default: tpubft/
Exit 1 with one line per violation. Wired into tier-1 by
tests/test_check_imports.py.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Iterator, List, Tuple

APPROVED = {"jax", "numpy"}
INTERNAL = {"tpubft", "tests", "tools", "benchmarks"}


def _stdlib_names() -> frozenset:
    return frozenset(sys.stdlib_module_names)  # 3.10+


def _is_type_checking_test(test: ast.expr) -> bool:
    """`if TYPE_CHECKING:` / `if typing.TYPE_CHECKING:` bodies never
    execute at runtime — imports there are annotations-only, not a
    collection-time dependency."""
    if isinstance(test, ast.Name):
        return test.id == "TYPE_CHECKING"
    if isinstance(test, ast.Attribute):
        return test.attr == "TYPE_CHECKING"
    return False


def _top_level_import_nodes(tree: ast.Module) -> Iterator[ast.stmt]:
    """Statements executed at import time: the module body plus every
    compound-statement body that runs during import — `if`/`else` (a
    version gate still executes), `for`/`while` (+else), `with`, and a
    `try`'s else/finally. EXCLUDED: `try:` bodies and their handlers
    (try/except ImportError is the sanctioned soft-import idiom),
    function/class bodies (lazy imports), and `if TYPE_CHECKING:`
    (never executes)."""
    stack: List[ast.stmt] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, ast.If):
            if not _is_type_checking_test(node.test):
                stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, (ast.For, ast.While)):
            stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, ast.With):
            stack.extend(node.body)
        elif isinstance(node, ast.Try):
            stack.extend(node.orelse)
            stack.extend(node.finalbody)


def _imported_roots(node: ast.stmt) -> Iterator[Tuple[str, int]]:
    if isinstance(node, ast.Import):
        for alias in node.names:
            yield alias.name.split(".")[0], node.lineno
    elif isinstance(node, ast.ImportFrom):
        if node.level:                       # relative import: internal
            return
        if node.module:
            yield node.module.split(".")[0], node.lineno


def find_violations(root: str) -> List[Tuple[str, int, str]]:
    """Walk `root` for .py files; return (path, lineno, module) for each
    module-level import of a non-stdlib, non-approved package."""
    stdlib = _stdlib_names()
    out: List[Tuple[str, int, str]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "rb") as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError as e:
                    out.append((path, e.lineno or 0, f"<syntax error: {e}>"))
                    continue
            for node in _top_level_import_nodes(tree):
                for mod, lineno in _imported_roots(node):
                    if (mod in stdlib or mod in APPROVED
                            or mod in INTERNAL):
                        continue
                    out.append((path, lineno, mod))
    return sorted(out)


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tpubft")
    violations = find_violations(root)
    for path, lineno, mod in violations:
        print(f"{path}:{lineno}: module-level import of third-party "
              f"package {mod!r} (use a function-level or try-guarded "
              f"import; approved always-on deps: {sorted(APPROVED)})")
    if violations:
        return 1
    print(f"OK: no module-level third-party imports under {root}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
