"""tpuprof — offline flight-dump analyzer.

Merges one or more flight-recorder dump artifacts (written by
`tpubft.utils.flight.dump` — automatically on stalled/degraded health
transitions and chaos-campaign red verdicts, or on demand via
`status get flight`) into:

  * a per-slot TIMELINE: every (replica, seq) lifecycle folded from the
    raw ring events, aligned across replicas on the wall clock (each
    dump anchors its monotonic event clock with a ts_epoch/mono_ns
    pair), so "replica 2 committed 40ms after replica 0" is a table
    row, not an archaeology session;
  * a STAGE-HISTOGRAM table: adm_wait / dispatch / prepare / commit /
    exec / reply percentiles over every completed slot in the dumps;
  * the KERNEL profile per dump (call counts, batch sizes, compile
    warmup vs warm time, breaker states at call time);
  * spans grouped by trace id (the cross-replica request join).

Usage:
  python tools/tpuprof.py DUMP.json [DUMP2.json ...] [--seq N]
                          [--limit 30]

Typical slow-slot investigation (docs/OPERATIONS.md has the full
recipe): grab `status get flight` from each replica (or take the
automatic dump a stalled-health transition wrote), run tpuprof over
all of them, find the slot whose total is the outlier in the timeline,
and read which stage ate the time.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tpubft.utils import flight  # noqa: E402

STAGES = flight.STAGES


def load_dump(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as fh:
        d = json.load(fh)
    d["_path"] = path
    return d


def _epoch_of(dump: Dict, t_ns: int) -> float:
    """Wall-clock time of a monotonic event timestamp, via the dump's
    anchor pair."""
    return dump["ts_epoch"] + (t_ns - dump["mono_ns"]) / 1e9


def fold_slots(dump: Dict) -> Dict[Tuple[int, int], Dict]:
    """Rebuild slot lifecycles from the dump's raw ring events (the
    same fold the live SlotTracker applies — flight.SlotTracker.fold is
    the shared stage math). Keyed (rid, seq)."""
    field_of = flight.SlotTracker._FIELD
    slots: Dict[Tuple[int, int], Dict] = {}
    for ring in dump.get("rings", []):
        rid = ring.get("rid", -1)
        for ev in ring.get("events", []):
            t_ns, code, seq, view, arg = ev
            field = field_of.get(code)
            if field is None:
                continue
            slot = slots.setdefault((rid, seq),
                                    {"rid": rid, "seq": seq, "view": view})
            slot.setdefault(field, t_ns)
            if code == flight.EV_COMMITTED:
                slot.setdefault("path", "fast" if arg else "slow")
    return slots


def _label(dump: Dict, rid: int) -> str:
    base = os.path.basename(dump["_path"])
    return f"{base}:r{rid}" if rid >= 0 else base


def timeline(dumps: List[Dict], seq_filter: Optional[int] = None,
             limit: int = 30) -> List[str]:
    """Per-slot rows merged across dumps, newest seqs last. Each row's
    t0 converts through ITS OWN dump's epoch/mono anchor (monotonic
    clocks are unrelated across processes), so cross-replica offsets
    are real wall-clock deltas."""
    rows: Dict[int, List[Tuple[str, Dict, Dict, Dict]]] = {}
    for d in dumps:
        for (rid, seq), slot in fold_slots(d).items():
            if seq_filter is not None and seq != seq_filter:
                continue
            stages = flight.SlotTracker.fold(slot)
            rows.setdefault(seq, []).append(
                (_label(d, rid), slot, stages, d))
    out = ["slot timeline (ms per stage; t0 = first event's wall clock)",
           f"{'seq':>6} {'replica':<28} {'t0':>10} "
           + " ".join(f"{s:>9}" for s in STAGES) + f" {'total':>9} path"]
    seqs = sorted(rows)
    if seq_filter is None and len(seqs) > limit:
        seqs = seqs[-limit:]
        out.insert(1, f"(showing the newest {limit} of {len(rows)} seqs; "
                      f"--limit raises)")
    base_epoch = None
    for d in dumps:
        for ring in d.get("rings", []):
            for ev in ring.get("events", []):
                e = _epoch_of(d, ev[0])
                base_epoch = e if base_epoch is None else min(base_epoch, e)
    for seq in seqs:
        for label, slot, stages, dump in sorted(
                rows[seq], key=lambda r: r[0]):
            ts = [v for k, v in slot.items()
                  if k not in ("rid", "seq", "view", "path")]
            t0 = ""
            if ts and base_epoch is not None:
                t0 = f"{_epoch_of(dump, min(ts)) - base_epoch:+.3f}s"
            # spec_overlap is an OVERLAY of commit (it ran concurrently)
            # — summing it would overstate the slot's wall clock and
            # disagree with the recorded total_ms
            total = sum(stages[s] for s in flight.PIPELINE_STAGES)
            out.append(
                f"{seq:>6} {label:<28} {t0:>10} "
                + " ".join(f"{stages[s]:>9.3f}" for s in STAGES)
                + f" {total:>9.3f} {slot.get('path', '?')}")
    return out


def stage_table(dumps: List[Dict]) -> List[str]:
    """Percentiles per stage over every completed slot in the dumps
    (the dumps' retained `slots.recent` records plus ring folds)."""
    vals: Dict[str, List[float]] = {s: [] for s in STAGES}
    for d in dumps:
        recents = d.get("slots", {}).get("recent", [])
        seen = set()
        for rec in recents:
            seen.add((rec.get("rid"), rec.get("seq")))
            for s in STAGES:
                vals[s].append(rec["stages_ms"].get(s, 0.0))
        for (rid, seq), slot in fold_slots(d).items():
            if (rid, seq) in seen or "replied" not in slot:
                continue
            stages = flight.SlotTracker.fold(slot)
            for s in STAGES:
                vals[s].append(stages[s])
    out = ["stage histogram (ms over all completed slots)",
           f"{'stage':<10} {'count':>7} {'avg':>9} {'p50':>9} "
           f"{'p95':>9} {'max':>9}"]
    for s in STAGES:
        v = sorted(vals[s])
        n = len(v)
        if not n:
            out.append(f"{s:<10} {0:>7}")
            continue
        out.append(f"{s:<10} {n:>7} {sum(v) / n:>9.3f} {v[n // 2]:>9.3f} "
                   f"{v[min(n - 1, int(n * 0.95))]:>9.3f} {v[-1]:>9.3f}")
    return out


def kernel_table(dumps: List[Dict]) -> List[str]:
    out = ["kernel profile",
           f"{'dump':<24} {'kind':<10} {'calls':>6} {'first(ms)':>10} "
           f"{'warm avg':>9} {'max':>9} {'batch avg':>10} {'breaker'}"]
    for d in dumps:
        base = os.path.basename(d["_path"])
        for kind, st in sorted(d.get("kernels", {}).items()):
            out.append(
                f"{base:<24} {kind:<10} {st['calls']:>6} "
                f"{st['first_call_ms']:>10.3f} {st['warm_avg_ms']:>9.3f} "
                f"{st['max_ms']:>9.3f} {st['batch_avg']:>10.1f} "
                f"{st.get('breaker_states', {})}")
    return out


def trace_table(dumps: List[Dict], limit: int = 20) -> List[str]:
    """Spans merged across dumps by trace id — the cross-replica
    request join (span epochs are wall-clock, directly comparable)."""
    traces: Dict[str, List[Tuple[str, Dict]]] = {}
    for d in dumps:
        base = os.path.basename(d["_path"])
        for sp in d.get("spans", []):
            traces.setdefault(sp["trace_id"], []).append((base, sp))
    out = [f"traces ({len(traces)} ids; newest {limit} shown)",
           f"{'trace':<20} {'spans':>6}  names"]
    for tid, sps in sorted(traces.items(),
                           key=lambda kv: max(s["epoch"]
                                              for _, s in kv[1]))[-limit:]:
        names = sorted({s["name"] for _, s in sps})
        out.append(f"{tid:<20} {len(sps):>6}  {','.join(names)}")
    return out


def render(paths: List[str], seq: Optional[int] = None,
           limit: int = 30) -> str:
    dumps = [load_dump(p) for p in paths]
    sections = [
        [f"tpuprof — {len(dumps)} dump(s): "
         + ", ".join(os.path.basename(p) for p in paths)],
        stage_table(dumps),
        timeline(dumps, seq_filter=seq, limit=limit),
        kernel_table(dumps),
        trace_table(dumps),
    ]
    return "\n\n".join("\n".join(s) for s in sections)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge flight-recorder dumps into slot timelines "
                    "and stage histograms")
    ap.add_argument("dumps", nargs="+", help="flight dump JSON files")
    ap.add_argument("--seq", type=int, default=None,
                    help="show only this consensus seqnum's timeline")
    ap.add_argument("--limit", type=int, default=30,
                    help="max seqs in the timeline (newest kept)")
    args = ap.parse_args(argv)
    print(render(args.dumps, seq=args.seq, limit=args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
