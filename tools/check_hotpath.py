"""Dispatcher hot-path lint: no parse/verify call sites in the
admitted-message handlers.

CLI/back-compat shim — the implementation now lives in the unified
analyzer framework (tools/tpulint/passes/hotpath.py; run everything
with `python -m tools.tpulint`). The admission plane exists so the
consensus dispatcher never pays `m.unpack()` or a SigManager
verification for admitted traffic; this lint rejects any direct
`unpack()` / `.verify()` / `.verify_batch()` call inside the hot-path
handlers, and flags a listed handler that disappears from the source
(the list must track the code). Inline fallbacks for the legacy
`admission_workers=0` path live in `_verify_*` seams OUTSIDE the hot
list.

Usage:
  python tools/check_hotpath.py           # lints the repo's tpubft/
Exit 1 with one line per violation. Wired into tier-1 by
tests/test_check_hotpath.py.
"""
from __future__ import annotations

import os
import sys
from typing import List

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.tpulint.passes import hotpath as _impl  # noqa: E402

# module-local copies: tests narrow/mutate these per loaded instance
# without touching the shared pass configuration
HOT_PATH = {k: set(v) for k, v in _impl.HOT_PATH.items()}
FORBIDDEN_CALLS = set(_impl.FORBIDDEN_CALLS)
FORBIDDEN_TELEMETRY = set(_impl.FORBIDDEN_TELEMETRY)


def find_violations(root: str):
    return _impl.find_violations(root, hot_path=HOT_PATH,
                                 forbidden=FORBIDDEN_CALLS,
                                 telemetry=FORBIDDEN_TELEMETRY)


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else _ROOT
    violations = find_violations(root)
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        return 1
    n = sum(len(v) for v in HOT_PATH.values())
    print(f"OK: no unpack/verify/span/f-string sites in {n} hot-path "
          f"handlers (telemetry rides flight.record only)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
