"""Dispatcher hot-path lint: no parse/verify call sites in the
admitted-message handlers.

The admission plane (tpubft/consensus/admission.py) exists so the single
consensus dispatcher — the thread all protocol state mutates on — never
pays `m.unpack()` or a SigManager verification for admitted traffic.
That property only survives refactors if it is enforced by construction:
this lint (tools/check_imports.py-style, wired into tier-1 by
tests/test_check_hotpath.py) parses the hot-path functions and rejects
any direct call to

  * `unpack(...)` / `m.unpack(...)`          (full message parse)
  * `<anything>.verify(...)` / `.verify_batch(...)`  (signature check)

inside them. Inline fallbacks for the legacy `admission_workers=0` path
are still allowed — they live in dedicated `_verify_*` helper seams
OUTSIDE the hot list, and the handlers reach them only when no admission
verdict is attached. Adding a new parse/verify to a handler forces the
author through that seam, keeping the control thread lean.

Usage:
  python tools/check_hotpath.py           # lints the repo's tpubft/
Exit 1 with one line per violation.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set, Tuple

# (module path, class name) -> function names forming the dispatcher's
# admitted-message hot path: the loop itself plus every handler an
# AdmittedMsg can reach synchronously on the dispatcher thread.
HOT_PATH: Dict[Tuple[str, str], Set[str]] = {
    ("tpubft/consensus/incoming.py", "Dispatcher"): {
        "_loop_body",
    },
    ("tpubft/consensus/replica.py", "Replica"): {
        "_on_admitted",
        "_dispatch_external",
        "_on_client_request",
        "_handle_client_request",
        "_post_admission",
        "_on_pre_prepare",
        "_on_share",
        "_handle_full_cert",
        "_on_checkpoint",
        "_on_time_opinion",
        "_on_ask_to_leave_view",
        "_on_view_change",
        "_on_new_view",
        "_on_restart_ready",
    },
}

FORBIDDEN_CALLS = {"unpack", "verify", "verify_batch"}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _functions(tree: ast.Module, class_name: str):
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield item


def find_violations(root: str) -> List[Tuple[str, int, str]]:
    out: List[Tuple[str, int, str]] = []
    for (rel, cls), fn_names in sorted(HOT_PATH.items()):
        path = os.path.join(root, rel)
        with open(path, "rb") as f:
            tree = ast.parse(f.read(), filename=path)
        found: Set[str] = set()
        for fn in _functions(tree, cls):
            if fn.name not in fn_names:
                continue
            found.add(fn.name)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and _call_name(node) in FORBIDDEN_CALLS:
                    out.append((
                        os.path.join(rel),
                        node.lineno,
                        f"{cls}.{fn.name} calls {_call_name(node)}() — "
                        f"hot-path handlers must consult the admission "
                        f"verdict / route through a _verify_* seam"))
        for missing in sorted(fn_names - found):
            # a renamed handler silently leaving the lint's coverage is
            # itself a violation: the list must track the code
            out.append((rel, 0,
                        f"{cls}.{missing} not found — update "
                        f"tools/check_hotpath.py HOT_PATH"))
    return sorted(out)


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = find_violations(root)
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        return 1
    n = sum(len(v) for v in HOT_PATH.values())
    print(f"OK: no unpack/verify call sites in {n} hot-path handlers")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
