# tools/ is a package so `python -m tools.tpulint` works from the repo
# root; the individual check_*.py lint CLIs remain directly runnable.
