"""Crashpoint lint: the registry, the seams, and the tests agree.

The recovery drills (tpubft/testing/campaign.py, tests) address
durability seams BY NAME — `crashpoint("vc.persist", ...)` in the
replica, `arm("vc.persist")` / `TPUBFT_CRASHPOINT=vc.persist` in the
harness. The whole scheme decays silently if those names drift: a
renamed seam turns the drill that covers it into a no-op that waits for
a crash that never comes (masked only by its timeout), and a registry
entry whose seam was refactored away reads as coverage that no longer
exists. This lint (wired into tier-1 by tests/test_check_crashpoints.py)
parses every module under tpubft/, benchmarks/ and tests/ and enforces:

  * every name passed to `crashpoint(...)` / `arm(...)` — and every
    name inside a TPUBFT_CRASHPOINT env value — is a string literal
    present in `crashpoints.REGISTRY`;
  * every REGISTRY name is threaded at >= 1 real seam (a
    `crashpoint("<name>")` call site outside tpubft/testing/);
  * zero scanned seams (wrong root, package rename) fails loudly
    rather than reporting a vacuous OK.

Name uniqueness is enforced structurally (REGISTRY is a dict) — what
this lint adds is the cross-file agreement a dict cannot see.

Usage:
  python tools/check_crashpoints.py [root]    # default: the repo root
Exit 1 with one line per violation.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set, Tuple

Violation = Tuple[str, int, str]

HOOK_FUNCS = {"crashpoint", "arm"}
SCAN_DIRS = ("tpubft", "benchmarks", "tests")
# seams live in production code: registry coverage is only satisfied by
# a call site outside the harness itself
HARNESS_PREFIXES = (os.path.join("tpubft", "testing") + os.sep,
                    "benchmarks" + os.sep, "tests" + os.sep)


def _literal_name(node: ast.Call) -> Tuple[bool, str]:
    """(is_literal, value) of the call's first positional arg / name=."""
    arg = node.args[0] if node.args else next(
        (kw.value for kw in node.keywords if kw.arg == "name"), None)
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return True, arg.value
    return False, ""


def _env_names(node: ast.AST) -> List[str]:
    """Crashpoint names inside string literals shaped like env specs:
    {"TPUBFT_CRASHPOINT": "name[:hit]"} dict displays."""
    names: List[str] = []
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            key = getattr(k, "value", None)
            is_env_key = key == "TPUBFT_CRASHPOINT" or (
                isinstance(k, ast.Name) and k.id == "ENV_VAR")
            if is_env_key and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                names.append(v.value.partition(":")[0])
    return names


def _scan_module(path: str, rel: str, registry: Set[str],
                 seams: Dict[str, int]) -> List[Violation]:
    with open(path, "rb") as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            return [(rel, e.lineno or 0, f"syntax error: {e.msg}")]
    out: List[Violation] = []
    in_harness = rel.startswith(HARNESS_PREFIXES)
    for node in ast.walk(tree):
        for name in _env_names(node):
            if name not in registry:
                out.append((rel, node.lineno,
                            f"TPUBFT_CRASHPOINT={name!r} names an "
                            f"unregistered crashpoint"))
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        called = (fn.id if isinstance(fn, ast.Name)
                  else fn.attr if isinstance(fn, ast.Attribute) else None)
        if called not in HOOK_FUNCS:
            continue
        is_lit, name = _literal_name(node)
        if not is_lit:
            # registry.REGISTRY-driven loops (the lint's own tests, a
            # drill iterating all seams) are fine for arm(); a seam
            # itself must be a greppable literal
            if called == "crashpoint":
                out.append((rel, node.lineno,
                            "crashpoint() seam name must be a string "
                            "literal (drills address seams by grep)"))
            continue
        if name not in registry:
            out.append((rel, node.lineno,
                        f"{called}({name!r}) references an unregistered "
                        f"crashpoint (add it to crashpoints.REGISTRY)"))
        elif called == "crashpoint" and not in_harness \
                and rel != os.path.join("tpubft", "testing",
                                        "crashpoints.py"):
            seams[name] = seams.get(name, 0) + 1
    return out


def _load_registry(root: str) -> Tuple[Set[str], List[Violation]]:
    """REGISTRY keys, AST-parsed from the root's own crashpoints.py (no
    import: the module under test must be the one under `root`, not
    whatever sys.modules cached)."""
    rel = os.path.join("tpubft", "testing", "crashpoints.py")
    path = os.path.join(root, rel)
    if not os.path.exists(path):
        return set(), [(rel, 0, "crashpoints.py not found — wrong root?")]
    with open(path, "rb") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign) or isinstance(node, ast.Assign):
            targets = ([node.target] if isinstance(node, ast.AnnAssign)
                       else node.targets)
            if any(isinstance(t, ast.Name) and t.id == "REGISTRY"
                   for t in targets) and isinstance(node.value, ast.Dict):
                keys = [k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)]
                return set(keys), []
    return set(), [(rel, 0, "REGISTRY dict literal not found")]


def find_violations(root: str) -> List[Violation]:
    registry, out = _load_registry(root)
    if out:
        return out
    seams: Dict[str, int] = {}
    scanned = 0
    for sub in SCAN_DIRS:
        top = os.path.join(root, sub)
        for dirpath, _dirnames, filenames in os.walk(top):
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                scanned += 1
                out.extend(_scan_module(path, rel, registry, seams))
    if not scanned:
        # a wrong root must FAIL, not report a vacuous OK
        out.append((root, 0, "no Python modules found to scan — wrong "
                             "root? (expected <root>/{%s}/**/*.py)"
                             % ",".join(SCAN_DIRS)))
        return sorted(out)
    for name in sorted(registry - set(seams)):
        out.append((os.path.join("tpubft", "testing", "crashpoints.py"), 0,
                    f"REGISTRY entry {name!r} is not threaded at any "
                    f"durability seam (phantom coverage — remove it or "
                    f"add the crashpoint() call)"))
    if not seams:
        out.append((root, 0, "zero crashpoint seams found outside the "
                             "harness — the recovery drills cover "
                             "nothing"))
    return sorted(out)


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    violations = find_violations(root)
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        return 1
    print("OK: crashpoint registry, seams and drills agree")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
