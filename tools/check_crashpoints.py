"""Crashpoint lint: the registry, the seams, and the tests agree.

CLI/back-compat shim — the implementation now lives in the unified
analyzer framework (tools/tpulint/passes/crashpoints.py; run everything
with `python -m tools.tpulint`). Enforced: every `crashpoint(...)` /
`arm(...)` name (and every TPUBFT_CRASHPOINT env literal) is a string
literal registered in crashpoints.REGISTRY; every REGISTRY entry is
threaded at >= 1 real seam outside the harness; zero scanned modules
fails loudly rather than reporting a vacuous OK.

Usage:
  python tools/check_crashpoints.py [root]    # default: the repo root
Exit 1 with one line per violation. Wired into tier-1 by
tests/test_check_crashpoints.py.
"""
from __future__ import annotations

import os
import sys
from typing import List

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.tpulint.passes import crashpoints as _impl  # noqa: E402

HOOK_FUNCS = _impl.HOOK_FUNCS
SCAN_DIRS = _impl.SCAN_DIRS
HARNESS_PREFIXES = _impl.HARNESS_PREFIXES


def find_violations(root: str):
    return _impl.find_violations(root)


def main(argv: List[str]) -> int:
    root = argv[1] if len(argv) > 1 else _ROOT
    violations = find_violations(root)
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        return 1
    print("OK: crashpoint registry, seams and drills agree")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
